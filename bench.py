"""Headline bench: SSB-style group-by scan rate on real TPU hardware.

Config 2 of BASELINE.json: lineorder `WHERE lo_quantity < 25 GROUP BY
lo_orderdate SUM(lo_revenue)` — filter + dense group-by aggregation, the
reference's hot path (BenchmarkQueriesSSQE shape). Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

vs_baseline: the reference publishes no absolute numbers (BASELINE.md).  We
normalize against 500M rows/sec — an optimistic estimate of a whole Java
server's scan-aggregate throughput on this query shape (Pinot's per-core JMH
scan rates are tens of millions of rows/sec; a 16-core server lands near
this).  vs_baseline = rows_per_sec / 5e8, i.e. 1.0 means parity with a full
Java server on one TPU chip; the north-star 10x target is vs_baseline >= 10.
"""
from __future__ import annotations

import json
import time

import numpy as np

JAVA_SERVER_ROWS_PER_SEC = 5e8  # assumed reference throughput (see docstring)
N_ROWS = 1 << 27  # 134M rows


def main() -> None:
    import jax

    from pinot_tpu.parallel.engine import DistributedEngine
    from pinot_tpu.parallel.stacked import StackedTable
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.sql.parser import parse_query

    rng = np.random.default_rng(42)
    n = N_ROWS
    schema = Schema(
        "lineorder",
        [
            FieldSpec("lo_orderdate", DataType.INT),
            FieldSpec("lo_quantity", DataType.INT),
            FieldSpec("lo_revenue", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {
        "lo_orderdate": (19920101 + rng.integers(0, 2406, n)).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
        "lo_revenue": rng.integers(100, 1_000_000, n).astype(np.int64),
    }

    ndev = len(jax.devices())
    stacked = StackedTable.build(schema, data, num_shards=ndev)
    engine = DistributedEngine()
    engine.register_table("lineorder", stacked)

    ctx = parse_query(
        "SELECT lo_orderdate, SUM(lo_revenue) FROM lineorder "
        "WHERE lo_quantity < 25 GROUP BY lo_orderdate LIMIT 2500"
    )

    engine.execute(ctx)  # warm-up: compile + HBM pin
    times = []
    for _ in range(8):
        t0 = time.perf_counter()
        r = engine.execute(ctx)
        times.append(time.perf_counter() - t0)
    assert r.rows, "bench query returned nothing"
    t = float(np.median(times))
    rows_per_sec = n / t

    print(
        json.dumps(
            {
                "metric": "ssb_groupby_rows_scanned_per_sec",
                "value": round(rows_per_sec, 1),
                "unit": "rows/sec",
                "vs_baseline": round(rows_per_sec / JAVA_SERVER_ROWS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
