"""Headline bench: SSB-style group-by scan rate on real TPU hardware.

Config 2 of BASELINE.json: lineorder `WHERE lo_quantity < 25 GROUP BY
lo_orderdate SUM(lo_revenue)` — filter + dense group-by aggregation, the
reference's hot path (BenchmarkQueriesSSQE shape). Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Measurement methodology (round 2): the axon relay to the TPU re-ships every
input buffer on every jitted CALL (~5-7 GB/s measured), so per-call timing
measures the tunnel, not the engine.  On a real TPU host the columns stay
pinned in HBM across queries (the design premise).  We therefore measure the
MARGINAL per-query time: run the compiled query kernel K times inside one
program (lax.fori_loop whose body indexes a per-iteration filter threshold,
defeating loop-invariant hoisting) and report (t_K - t_1) / (K - 1).  The
host reduce tail is group-table-sized (row-count independent, ~1ms at 2406
groups) and excluded like Pinot's JMH benches exclude JSON rendering.

vs_baseline: the reference publishes no absolute numbers (BASELINE.md).  We
normalize against 500M rows/sec — an optimistic estimate of a whole Java
server's scan-aggregate throughput on this query shape (Pinot's per-core JMH
scan rates are tens of millions of rows/sec; a 16-core server lands near
this).  vs_baseline = rows_per_sec / 5e8; the north-star 10x target is
vs_baseline >= 10.  Running the reference's JMH suite in this image is not
possible (no Maven repo / zero egress); see BASELINE.md.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

JAVA_SERVER_ROWS_PER_SEC = 5e8  # assumed reference throughput (see docstring)
N_ROWS = int(os.environ.get("BENCH_ROWS", 1 << 27))  # 134M default; 1<<30 for the 1B run
# (the marginal-rate metric is row-count independent; the 1B-row datapoint is
# recorded in BASELINE.md — default size keeps driver runtime bounded because
# every jitted call re-ships inputs through the axon relay)
K_ITERS = 8


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pinot_tpu.parallel.engine import DistributedEngine
    from pinot_tpu.parallel.stacked import StackedTable
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.sql.parser import parse_query

    rng = np.random.default_rng(42)
    n = N_ROWS
    schema = Schema(
        "lineorder",
        [
            FieldSpec("lo_orderdate", DataType.INT),
            FieldSpec("lo_quantity", DataType.INT),
            FieldSpec("lo_revenue", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {
        "lo_orderdate": (19920101 + rng.integers(0, 2406, n)).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
        "lo_revenue": rng.integers(100, 1_000_000, n).astype(np.int64),
    }

    ndev = len(jax.devices())
    stacked = StackedTable.build(schema, data, num_shards=ndev)
    engine = DistributedEngine()
    engine.register_table("lineorder", stacked)

    ctx = parse_query(
        "SELECT lo_orderdate, SUM(lo_revenue) FROM lineorder "
        "WHERE lo_quantity < 25 GROUP BY lo_orderdate LIMIT 2500"
    )

    r = engine.execute(ctx)  # full-path warm-up: compile + correctness
    assert r.rows, "bench query returned nothing"

    # ---- marginal kernel timing ---------------------------------------
    plan = engine._plan(ctx, stacked)
    cols, valid = stacked.to_device(engine.mesh, engine.axis, plan.needed_columns)
    base_params = {
        k: jax.device_put(v, NamedSharding(engine.mesh, P())) for k, v in plan.params.items()
    }
    # per-iteration filter thresholds (hi code of `lo_quantity < X` wobbles
    # by i % 2) so the loop body depends on the index — no hoisting
    hi_key = next(k for k in base_params if k.endswith(".hi"))

    def timed_loop(k_iters: int):
        def run(cols, valid, params):
            def body(i, acc):
                p = dict(params)
                p[hi_key] = params[hi_key] - (i % 2).astype(jnp.int32)
                presence, partials = plan.fn(cols, valid, p)
                leaves = jax.tree_util.tree_leaves((presence, partials))
                return acc + sum(jnp.sum(l).astype(jnp.float64) for l in leaves)

            return lax.fori_loop(0, k_iters, body, jnp.float64(0))

        fn = jax.jit(run, static_argnums=())
        out = fn(cols, valid, base_params)
        jax.device_get(out)  # compile + first transfer
        ts = []
        for _ in range(2):
            t0 = time.perf_counter()
            out = fn(cols, valid, base_params)
            jax.device_get(out)
            ts.append(time.perf_counter() - t0)
        return float(np.min(ts))

    t_k = timed_loop(K_ITERS)
    t_1 = timed_loop(1)
    per_query = max((t_k - t_1) / (K_ITERS - 1), 1e-9)
    rows_per_sec = n / per_query

    print(
        json.dumps(
            {
                "metric": "ssb_groupby_rows_scanned_per_sec",
                "value": round(rows_per_sec, 1),
                "unit": "rows/sec",
                "vs_baseline": round(rows_per_sec / JAVA_SERVER_ROWS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
