"""Headline bench: SSB-style group-by scan rate on real TPU hardware.

Config 2 of BASELINE.json: lineorder `WHERE lo_quantity < 25 GROUP BY
lo_orderdate SUM(lo_revenue)` — filter + dense group-by aggregation, the
reference's hot path (BenchmarkQueriesSSQE shape).  The filter column
carries a RANGE INDEX (round 3): the compiled kernel reads prefix-bitmap
word slices instead of scanning codes, and `filter_index_uses` in the
output proves the indexed path ran.  Prints ONE JSON line.

Two timings are reported (round-3 methodology fix — both recorded so rounds
stay comparable):

  value / value_marginal  — MARGINAL per-query kernel time: K queries run
      inside one program (lax.fori_loop whose body depends on the loop index
      so XLA cannot hoist it); median slope over >=3 interleaved
      (t_1, t_K) pairs, each the min of 3 runs (round-5 hardening: a
      single pair understated r4 by 21x under relay contention).  The
      estimate is cross-checked against the subtraction-free amortized
      floor n*K/min(t_K); >25% disagreement triggers re-measurement, and
      the reported value is max(median slope, amortized floor) with the
      pair spread in `run_variance`.  Excludes input transfer and the
      host reduce tail (group-table-sized, row-count independent).
      Rationale: the axon relay re-ships every input buffer per jitted
      call (~5-7 GB/s), which measures the tunnel, not the engine; on a
      real TPU host columns stay pinned in HBM.
  value_e2e — full DistributedEngine.execute() wall clock (parse reuse,
      kernel, device_get, broker reduce), min of 3 after warm-up.  On the
      relay this includes per-call buffer re-shipping; on a real TPU host
      it is the honest query latency.

vs_baseline: the reference publishes no absolute numbers (BASELINE.md).
The denominator is the ASSUMED 5e8 rows/s whole-server Java scan rate
(kept constant across rounds for comparability).  To bracket the
assumption, `cpu_proxy_rows_per_sec` measures a single-core numpy
scan-aggregate of the same query in-image (extrapolated from a 8M-row
sample); BASELINE.md records the provenance of both.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

JAVA_SERVER_ROWS_PER_SEC = 5e8  # assumed reference throughput (see docstring)
N_ROWS = int(os.environ.get("BENCH_ROWS", 1 << 27))  # 134M default; 1<<30 for the 1B run
K_ITERS = 8


def _cpu_proxy(sample_rows: int = 1 << 23) -> float:
    """Single-core numpy scan-aggregate proxy for the Java-server denominator:
    same query shape (mask + filtered segmented sum) on a smaller sample."""
    rng = np.random.default_rng(7)
    od = rng.integers(0, 2406, sample_rows).astype(np.int32)
    qty = rng.integers(1, 51, sample_rows).astype(np.int8)
    rev = rng.integers(100, 1_000_000, sample_rows).astype(np.int64)
    t0 = time.perf_counter()
    mask = qty < 25
    np.bincount(od[mask], weights=rev[mask], minlength=2406)
    dt = time.perf_counter() - t0
    return sample_rows / dt


def _overload_bench() -> dict:
    """Offered-load sweep through the broker's admission controller (round-11
    overload governance): estimate single-stream capacity on a small broker
    cluster, then offer 0.5x / 1x / 3x that rate with the token bucket
    clocked by the *simulated* arrival times (deterministic: admission
    depends only on the arrival schedule, not host speed).  Reports
    admitted/shed/killed counts and the admitted-query p99 — the tracked
    proof that 3x overload sheds with structured 429s instead of queueing
    unboundedly or crashing."""
    from pinot_tpu.cluster.admission import (
        AdmissionController,
        QueryKilledError,
        ReservationError,
        TooManyRequestsError,
        estimate_query_cost,
    )
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.coordinator import Coordinator
    from pinot_tpu.cluster.server import ServerInstance
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.spi.config import SegmentsConfig, TableConfig
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.sql.parser import parse_query

    schema = Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )
    coord = Coordinator(replication=2)
    for i in range(2):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(schema, TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    rng = np.random.default_rng(11)
    rows = int(os.environ.get("BENCH_OVERLOAD_ROWS", 50_000))
    for i in range(4):
        coord.add_segment(
            "t",
            build_segment(
                schema,
                {
                    "city": rng.choice(["sf", "nyc", "la"], rows).astype(object),
                    "v": rng.integers(0, 100, rows),
                    "ts": 1_700_000_000_000 + rng.integers(0, 86_400_000, rows).astype(np.int64),
                },
                f"seg{i}",
            ),
        )
    broker = Broker(coord)

    # distinct literal per query: misses the result cache every time (full
    # scatter path) while the parameterized plan cache stays warm
    def sql_at(i: int) -> str:
        return (
            "SELECT city, COUNT(*), SUM(v) FROM t "
            f"WHERE v < {50 + i % 40} GROUP BY city ORDER BY city"
        )

    broker.query(sql_at(0))  # warm: parse, plan, compile

    # ---- uncontended baseline (governor at env defaults: admission off) --
    n_base = 40
    base_ts = []
    for i in range(n_base):
        t0 = time.perf_counter()
        broker.query(sql_at(i))
        base_ts.append((time.perf_counter() - t0) * 1000)
    uncontended_p99 = float(np.percentile(base_ts, 99))
    capacity_qps = 1000.0 / float(np.median(base_ts))

    ctx = parse_query(sql_at(0))
    unit_cost = estimate_query_cost(ctx, coord.tables["t"].segment_meta.values()).units

    sweep = []
    for mult in (0.5, 1.0, 3.0):
        # fresh bucket per load point, clocked by the simulated arrival
        # schedule; max_queue=0 = admit-or-shed (the sim clock never
        # advances inside a wait, so queueing would never drain)
        sim = [0.0]
        adm = AdmissionController(
            rate_units_per_s=capacity_qps * unit_cost,
            burst_units=2 * unit_cost,
            max_queue=0,
        )
        adm.clock = lambda: sim[0]
        broker.governor.admission = adm
        offered_qps = mult * capacity_qps
        admitted = shed = killed = 0
        admitted_ms = []
        for i in range(120):
            sim[0] += 1.0 / offered_qps  # next arrival
            t0 = time.perf_counter()
            try:
                broker.query(sql_at(i))
            except TooManyRequestsError:
                shed += 1
            except (QueryKilledError, ReservationError):
                killed += 1
            else:
                admitted += 1
                admitted_ms.append((time.perf_counter() - t0) * 1000)
        sweep.append(
            {
                "offered_x": mult,
                "offered_qps": round(offered_qps, 1),
                "admitted": admitted,
                "shed": shed,
                "killed": killed,
                "admitted_p99_ms": (
                    round(float(np.percentile(admitted_ms, 99)), 3) if admitted_ms else None
                ),
            }
        )
    broker.governor.admission = AdmissionController()  # back to permissive
    return {
        "uncontended_p99_ms": round(uncontended_p99, 3),
        "capacity_qps_est": round(capacity_qps, 1),
        "sweep": sweep,
    }


def _tail_latency_bench() -> dict:
    """Tail-latency section (round-15 tail tolerance): one of two replicas
    degraded to ~10x latency by a seeded FaultPlan jitter rule, measured
    three ways — fault-free baseline, degraded without hedging, degraded
    with hedged scatter (delay derived from the healthy peer's observed
    p95).  Brownout deprioritization is disabled for the sweep so it
    isolates hedging from routing-away; the brownout path has its own
    tests.  Reports p50/p99 per leg plus the hedge rate and wasted-work %
    — `hedged_p99_ms` is a lower-is-better metric in the `cli perf
    --check` regression gate."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.coordinator import Coordinator
    from pinot_tpu.cluster.faults import FaultPlan
    from pinot_tpu.cluster.server import ServerInstance
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.spi.config import SegmentsConfig, TableConfig
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.utils.metrics import METRICS

    rows = int(os.environ.get("BENCH_TAIL_ROWS", 5_000))
    n_meas = int(os.environ.get("BENCH_TAIL_QUERIES", 60))
    slow_mult = float(os.environ.get("BENCH_TAIL_SLOW_MULT", "10"))

    def make_cluster():
        schema = Schema(
            "t",
            [
                FieldSpec("city", DataType.STRING),
                FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
                FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
            ],
        )
        coord = Coordinator(replication=2)
        for i in range(2):
            coord.register_server(ServerInstance(f"server{i}"))
        coord.add_table(schema, TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
        rng = np.random.default_rng(11)
        for i in range(4):
            coord.add_segment(
                "t",
                build_segment(
                    schema,
                    {
                        "city": rng.choice(["sf", "nyc", "la"], rows).astype(object),
                        "v": rng.integers(0, 100, rows),
                        "ts": 1_700_000_000_000
                        + rng.integers(0, 86_400_000, rows).astype(np.int64),
                    },
                    f"seg{i}",
                ),
            )
        return coord, Broker(coord)

    def sql_at(i: int) -> str:
        # distinct literal per query: misses the result cache every time
        return (
            "SELECT city, COUNT(*), SUM(v) FROM t "
            f"WHERE v < {50 + i % 40} GROUP BY city ORDER BY city"
        )

    hedge_counters = ("hedgesLaunched", "hedgeWins", "hedgesCancelled", "hedgesDenied")

    def run_leg(slow_ms: float, hedge: bool) -> dict:
        coord, broker = make_cluster()
        if slow_ms > 0:
            # balanced round-robin routing sends 2 of 4 segments to each
            # server, so every query's scatter includes the slow replica
            FaultPlan(seed=17).jitter("server0", base_ms=slow_ms, sigma=0.5).attach(coord)
        broker.health.brownout_factor = float("inf")  # isolate hedging
        hc = broker.hedge
        hc.enabled_default = hedge
        hc.budget_pct = 60.0  # 2-server scatter: 1 hedge per query = 50% of launches
        c0 = {k: METRICS.counter(f"broker.{k}").value for k in hedge_counters}
        w0 = (
            METRICS.timer("broker.hedgeWastedMs").total_ms
            + METRICS.timer("broker.hedgeCancelMs").total_ms
        )
        leg_t0 = time.perf_counter()
        broker.query(sql_at(0))  # warm: parse, plan, compile
        # fill the per-(table, server) latency windows so the hedge delay is
        # derived from observed peer quantiles rather than an env override
        for i in range(hc.min_samples + 2):
            broker.query(sql_at(i))
        ts = []
        for i in range(n_meas):
            t0 = time.perf_counter()
            broker.query(sql_at(100 + i))
            ts.append((time.perf_counter() - t0) * 1000)
        leaked = broker.hedge_drain()
        leg_wall_ms = (time.perf_counter() - leg_t0) * 1000
        counts = {k: METRICS.counter(f"broker.{k}").value - c0[k] for k in hedge_counters}
        wasted_ms = (
            METRICS.timer("broker.hedgeWastedMs").total_ms
            + METRICS.timer("broker.hedgeCancelMs").total_ms
            - w0
        )
        snap = hc.snapshot()
        return {
            "p50_ms": round(float(np.percentile(ts, 50)), 3),
            "p99_ms": round(float(np.percentile(ts, 99)), 3),
            "hedge_rate": round(snap["hedges"] / max(1, snap["primaries"]), 4),
            # share of all compute-ms (wall + discarded attempt time) that
            # losing attempts burned before cooperative cancel reclaimed them
            "wasted_work_pct": round(100.0 * wasted_ms / max(1e-9, wasted_ms + leg_wall_ms), 2),
            "leaked_launches": leaked,
            **{k: v for k, v in counts.items()},
        }

    fault_free = run_leg(slow_ms=0.0, hedge=False)
    # self-calibrating fault: the slow replica's jitter base is 10x the
    # measured fault-free median, i.e. "one replica at 10x latency"
    slow_ms = round(slow_mult * max(0.5, fault_free["p50_ms"]), 3)
    unhedged = run_leg(slow_ms=slow_ms, hedge=False)
    hedged = run_leg(slow_ms=slow_ms, hedge=True)
    ff_p99 = max(1e-9, fault_free["p99_ms"])
    return {
        "slow_replica_ms": slow_ms,
        "fault_free": fault_free,
        "unhedged": unhedged,
        "hedged": hedged,
        "hedge_rate": hedged["hedge_rate"],
        "wasted_work_pct": hedged["wasted_work_pct"],
        "p99_vs_fault_free": {
            "unhedged_x": round(unhedged["p99_ms"] / ff_p99, 2),
            "hedged_x": round(hedged["p99_ms"] / ff_p99, 2),
        },
    }


def _concurrent_qps_bench() -> dict:
    """Sustained QPS under 100+ simultaneous clients (round-12 concurrent
    serving tier).  Two modes over identical same-fingerprint workloads
    (one query shape, distinct literals — the regime cross-query batching
    exists for):

      batched:   clients call broker.submit(sql).result(); in-flight
                 same-shape queries coalesce in the MicroBatcher (real
                 wall-clock window, PINOT_TPU_BATCH_WAIT_MS) and execute
                 as ONE vmapped plan launch per segment
      unbatched: thread-per-request broker.query(sql) — the synchronous
                 scatter path every client used before this tier

    A mixed-shape leg runs the batched path over three distinct shapes to
    exercise per-fingerprint grouping under a storm.  Reports sustained
    QPS + client-observed p50/p95/p99 per mode and the speedup ratio;
    `batched_qps` / `batch_speedup` feed the bench-history gate."""
    import threading

    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.coordinator import Coordinator
    from pinot_tpu.cluster.server import ServerInstance
    from pinot_tpu.query import executor as sse_executor
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.spi.config import SegmentsConfig, TableConfig
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.utils.metrics import METRICS

    schema = Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )
    coord = Coordinator(replication=2)
    for i in range(2):
        coord.register_server(ServerInstance(f"server{i}"))
    coord.add_table(schema, TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    rng = np.random.default_rng(23)
    rows = int(os.environ.get("BENCH_QPS_ROWS", 20_000))
    for i in range(4):
        coord.add_segment(
            "t",
            build_segment(
                schema,
                {
                    "city": rng.choice(["sf", "nyc", "la"], rows).astype(object),
                    "v": rng.integers(0, 100, rows),
                    "ts": 1_700_000_000_000 + rng.integers(0, 86_400_000, rows).astype(np.int64),
                },
                f"seg{i}",
            ),
        )
    broker = Broker(coord)

    shapes = [
        lambda i: (
            "SELECT city, COUNT(*), SUM(v) FROM t "
            f"WHERE v < {50 + i % 40} GROUP BY city ORDER BY city"
        ),
        lambda i: f"SELECT COUNT(*), MAX(v) FROM t WHERE v > {i % 40}",
        lambda i: f"SELECT city, SUM(v) FROM t WHERE v >= {i % 30} GROUP BY city ORDER BY city LIMIT 2",
    ]

    n_clients = int(os.environ.get("BENCH_QPS_CLIENTS", 120))
    reqs = int(os.environ.get("BENCH_QPS_REQS", 2))

    def run_mode(issue, sql_for) -> dict:
        """All clients start behind one barrier; sustained QPS is completed
        requests over the span from release to last join."""
        lats = []
        lock = threading.Lock()
        barrier = threading.Barrier(n_clients + 1)

        def client(cid):
            barrier.wait()
            for r in range(reqs):
                sql = sql_for(cid * reqs + r)
                t0 = time.perf_counter()
                issue(sql)
                dt = (time.perf_counter() - t0) * 1000.0
                with lock:
                    lats.append(dt)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        arr = np.asarray(lats)
        return {
            "qps": round(len(lats) / wall, 1),
            "wall_s": round(wall, 4),
            "requests": len(lats),
            "p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p95_ms": round(float(np.percentile(arr, 95)), 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
        }

    # warm every shape through both paths so neither mode pays compiles:
    # one sync query (base plan) + one full-width batch (vmapped plan)
    for sh in shapes:
        broker.query(sh(0))
        futs = [broker.submit(sh(j)) for j in range(sse_executor.batch_width())]
        broker.drain_batches()
        for f in futs:
            f.result()

    sse_executor.BATCH_AUDIT.reset()
    b0 = METRICS.counter("broker.batches").value
    batched = run_mode(lambda s: broker.submit(s).result(), shapes[0])
    batched["batches"] = METRICS.counter("broker.batches").value - b0
    batched["batch_compiles"] = sse_executor.BATCH_AUDIT.snapshot()["compiles"]
    unbatched = run_mode(broker.query, shapes[0])
    mixed = run_mode(
        lambda s: broker.submit(s).result(), lambda i: shapes[i % len(shapes)](i)
    )
    speedup = round(batched["qps"] / unbatched["qps"], 3) if unbatched["qps"] else None
    return {
        "clients": n_clients,
        "requests_per_client": reqs,
        "rows_per_segment": rows,
        "batched": batched,
        "unbatched": unbatched,
        "mixed_shapes_batched": mixed,
        "batch_speedup": speedup,
    }


def _mesh_scaling_bench() -> dict:
    """2-D (replica x shard) mesh scale-out section (multi-host tentpole).

    Three measurements over one dataset:

      topologies: warm scan rows/s per mesh shape — 1-D "seg" 8-dev
                  baseline vs 2x4 / 4x2 / 1x8 two-axis meshes, asserting
                  BIT-IDENTICAL rows per topology (the hierarchical
                  shard-then-replica combine must not change results)
      shard axis: rows/s at full shard width vs a single-device mesh —
                  `mesh_shard_speedup` is the capacity-scaling ratio
      replica axis: concurrent QPS through ReplicatedEngine at R=2 (two
                  4-device rows, whole batches round-robin across rows)
                  vs R=1 — `mesh_replica_qps_scale` is the QPS ratio

    HONESTY NOTE: in-image the 8 "devices" are XLA host-platform threads on
    however many cores the container grants (often ONE), so both ratios
    measure collective/dispatch overhead, not real parallel speedup — expect
    ~1.0 and read them as regression canaries (a broken hierarchical combine
    or a row that stops serving moves them), not as scaling claims.  Real
    per-axis scaling needs real hardware (ICI shard rows, DCN replica rows).
    """
    import threading

    from pinot_tpu.parallel.engine import DistributedEngine, ReplicatedEngine
    from pinot_tpu.parallel.mesh import default_mesh, make_mesh2d
    from pinot_tpu.parallel.stacked import StackedTable
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.sql.parser import parse_query

    rng = np.random.default_rng(31)
    rows = int(os.environ.get("BENCH_MESH_ROWS", 1 << 20))
    schema = Schema(
        "t",
        [
            FieldSpec("k", DataType.INT),
            FieldSpec("m", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {
        "k": rng.integers(0, 1024, rows).astype(np.int32),
        "m": rng.integers(1, 1000, rows).astype(np.int64),
    }
    stacked = StackedTable.build(schema, data, num_shards=8)
    ctx = parse_query("SELECT k, COUNT(*), SUM(m) FROM t WHERE m > 100 GROUP BY k LIMIT 1100")

    def scan_leg(mesh) -> tuple:
        eng = DistributedEngine(mesh)
        eng.register_table("t", stacked)
        res = eng.execute(ctx)  # compile + correctness
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            eng.execute(ctx)
            ts.append(time.perf_counter() - t0)
        return round(rows / float(np.min(ts)), 1), [tuple(r) for r in res.rows]

    base_rps, base_rows = scan_leg(default_mesh())
    topologies = {"seg8": {"rows_per_sec": base_rps, "bit_identical": True}}
    for r, s in [(1, 8), (2, 4), (4, 2)]:
        rps, out = scan_leg(make_mesh2d(r, s))
        same = out == base_rows
        topologies[f"{r}x{s}"] = {"rows_per_sec": rps, "bit_identical": same}
        assert same, f"mesh {r}x{s} drifted from the 1-D baseline"

    one_dev_rps, _ = scan_leg(default_mesh(num_devices=1))
    shard_speedup = round(topologies["1x8"]["rows_per_sec"] / one_dev_rps, 3)

    def qps_leg(num_replicas: int) -> dict:
        eng = ReplicatedEngine(num_replicas=num_replicas)
        eng.register_table("t", stacked)
        n_clients = int(os.environ.get("BENCH_MESH_CLIENTS", 8))
        reqs = int(os.environ.get("BENCH_MESH_REQS", 4))
        # warm every replica row's plan/device caches out of the timed span
        for _ in range(num_replicas):
            eng.execute(ctx)
        lats = []
        lock = threading.Lock()
        barrier = threading.Barrier(n_clients + 1)

        def client():
            barrier.wait()
            for _ in range(reqs):
                t0 = time.perf_counter()
                eng.execute(ctx)
                dt = (time.perf_counter() - t0) * 1000.0
                with lock:
                    lats.append(dt)

        threads = [threading.Thread(target=client, daemon=True) for _ in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        arr = np.asarray(lats)
        return {
            "replicas": num_replicas,
            "qps": round(len(lats) / wall, 1),
            "p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
        }

    qps_r1 = qps_leg(1)
    qps_r2 = qps_leg(2)
    replica_scale = round(qps_r2["qps"] / qps_r1["qps"], 3) if qps_r1["qps"] else None
    return {
        "rows": rows,
        "topologies": topologies,
        "single_device_rows_per_sec": one_dev_rps,
        "mesh_shard_speedup": shard_speedup,
        "qps_r1": qps_r1,
        "qps_r2": qps_r2,
        "mesh_replica_qps_scale": replica_scale,
    }


def _working_set_sweep() -> dict:
    """Tiered-storage capacity sweep (round-14 tentpole).

    HBM is now a cost-aware cache over host RAM (segment/residency.py):
    macro-batch slices are staged through an async double-buffered copy
    stream and evicted by coldness when the budget fills.  This section
    sizes the sequential-scan working set W empirically (resident bytes
    after an unbounded-budget scan), then reruns the same group-by scan
    with the cache budget at 2W / W / W/4 — i.e. the working set at
    0.5x / 1x / 4x of HBM — and reports the rows/s degradation curve,
    the prefetch-hit rate of the staging stream, and staging-stall time.
    Every leg must be bit-exact against an untiered (hbm_cache_bytes=0,
    full-pinning) reference: eviction churn may cost throughput, never
    correctness.  bench_record lifts the 1x/4x rows/s and the 4x
    prefetch-hit rate into the gate metrics.
    """
    import jax

    from pinot_tpu.parallel.engine import DistributedEngine
    from pinot_tpu.parallel.stacked import StackedTable
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.utils.metrics import METRICS

    rng = np.random.default_rng(7)
    # capacity behaviour is about ratios, not scale — cap the table so the
    # sweep stays cheap inside the CPU smoke run (BENCH_ROWS=1<<20)
    n = min(N_ROWS, 1 << 22)
    schema = Schema(
        "ws",
        [
            FieldSpec("g", DataType.INT),
            FieldSpec("m", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {
        "g": rng.integers(0, 512, n).astype(np.int32),
        "m": rng.integers(0, 1 << 20, n).astype(np.int64),
    }
    sql = "SELECT g, COUNT(*), SUM(m) FROM ws GROUP BY g ORDER BY g LIMIT 600"
    ndev = len(jax.devices())
    # ~16 macro-batches (~12 B/doc: packed g codes + raw m): the 4x leg
    # keeps only ~4 slices resident, so the copy stream runs continuously
    # while earlier batches scan — the double-buffering regime under test
    launch_bytes = max(4096, (12 * max(n // ndev, 1)) // 16)

    def build(cache_bytes):
        eng = DistributedEngine(launch_bytes=launch_bytes, hbm_cache_bytes=cache_bytes)
        eng.register_table("ws", StackedTable.build(schema, dict(data), eng.num_devices))
        return eng

    ref_eng = build(0)  # tiering disabled: the pre-r14 full-pinning path
    ref_rows = ref_eng.query(sql).rows

    probe = build(1 << 40)  # effectively unbounded budget: measures W
    assert probe.query(sql).rows == ref_rows, "tiered probe diverged from untiered reference"
    wset = int(probe.residency.resident_bytes)
    probe.residency.shutdown()

    iters = max(2, min(K_ITERS, 4))
    legs = {}
    for label, budget in (
        ("0.5x", 2 * wset),
        ("1x", wset + (64 << 10)),  # dict-page headroom: fully resident
        ("4x", max(4 * launch_bytes, wset // 4)),
    ):
        eng = build(budget)
        # cold pass pays compiles + the first staging wave; the timed loop
        # measures the steady state each leg is meant to expose
        assert eng.query(sql).rows == ref_rows, f"tiered {label} leg diverged"
        h0 = METRICS.counter("engine.prefetchHits").value
        s0 = METRICS.counter("engine.stagingStalls").value
        st0 = METRICS.snapshot()["histograms"].get("residency.stagingStallMs", {})
        t0 = time.perf_counter()
        for _ in range(iters):
            assert eng.query(sql).rows == ref_rows, f"tiered {label} leg diverged"
        wall = time.perf_counter() - t0
        hits = METRICS.counter("engine.prefetchHits").value - h0
        stalls = METRICS.counter("engine.stagingStalls").value - s0
        st1 = METRICS.snapshot()["histograms"].get("residency.stagingStallMs", {})
        stall_ms = st1.get("count", 0) * st1.get("meanMs", 0.0) - st0.get(
            "count", 0
        ) * st0.get("meanMs", 0.0)
        snap = eng.residency.snapshot()
        legs[label] = {
            "budget_bytes": int(budget),
            "rows_per_sec": round(n * iters / wall, 1),
            "prefetch_hits": int(hits),
            "staging_stalls": int(stalls),
            "prefetch_hit_rate": round(hits / (hits + stalls), 3) if hits + stalls else 1.0,
            "staging_stall_ms": round(max(stall_ms, 0.0), 3),
            "evictions": snap["evictions"],
            "bit_exact": True,
        }
        eng.residency.shutdown()
    return {
        "rows": n,
        "working_set_bytes": wset,
        "launch_bytes": int(launch_bytes),
        "iters_per_leg": iters,
        "legs": legs,
    }


def _failover_bench() -> dict:
    """Coordinator HA failover drill (round-18 tentpole).

    One meta_dir, a leader and a hot standby sharing a SIMULATED clock
    (lease TTL 2s), brokers behind a CoordinatorHandle whose sleep hook
    advances that clock — the whole failover runs in virtual time, so the
    blackout figure measures the protocol (lease expiry + standby
    replay-to-tip + handle adoption), not host scheduling noise:

      1. FaultPlan.pause_leader freezes the leader (no lease renews, the
         control plane refuses with NotLeaderError, the data plane keeps
         serving the last versioned view)
      2. one control-plane write fires through the handle; every park
         backoff advances the sim clock AND issues one data-plane query
         through the broker (the concurrent load), until the standby's
         election tick sees the expired lease and promotes
      3. the resumed old leader's next journaled write must FENCE
         (FencedEpochError) — split-brain cannot reach the journal

    Reports control-plane blackout ms (sim delta from pause to the write
    landing on the new leader), data-plane success rate during the
    blackout, and the standby's replay-to-tip ms; `failover_blackout_ms`
    joins GATE_METRICS_LOWER in the bench-history gate."""
    import tempfile

    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.coordinator import Coordinator
    from pinot_tpu.cluster.election import CoordinatorHandle, FencedEpochError
    from pinot_tpu.cluster.faults import FaultPlan
    from pinot_tpu.cluster.server import ServerInstance
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.spi.config import SegmentsConfig, TableConfig
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.utils.metrics import METRICS

    tmp = tempfile.mkdtemp(prefix="pinot-failover-")
    sim = [0.0]

    def clock() -> float:
        return sim[0]

    ttl_s = 2.0
    leader = Coordinator(
        replication=2,
        meta_dir=os.path.join(tmp, "meta"),
        deep_store=os.path.join(tmp, "deep"),
        node_id="coord-a",
        lease_ttl_s=ttl_s,
        clock=clock,
    )
    plan = FaultPlan(seed=7).attach_coordinator(leader)

    probes = {"ok": 0, "bad": 0}
    in_blackout = [False]
    sql = "SELECT city, COUNT(*), SUM(v) FROM t GROUP BY city ORDER BY city"
    expected = []  # filled after warm-up

    def sim_sleep(s: float) -> None:
        sim[0] += s
        if in_blackout[0]:
            # the concurrent query load: one data-plane probe per park
            # backoff, served off the last routing view while leaderless
            try:
                r = broker.query(sql)
                probes["ok" if list(r.rows) == expected else "bad"] += 1
            except Exception:  # noqa: BLE001 — a refused probe is the datum
                probes["bad"] += 1

    handle = CoordinatorHandle([leader], sleep=sim_sleep, clock=clock)
    schema = Schema(
        "t",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )
    for i in range(2):
        handle.register_server(
            ServerInstance(f"server{i}", data_dir=os.path.join(tmp, f"server{i}"))
        )
    handle.add_table(schema, TableConfig(name="t", segments=SegmentsConfig(time_column="ts")))
    rng = np.random.default_rng(19)
    rows = int(os.environ.get("BENCH_FAILOVER_ROWS", 2_000))
    for i in range(4):
        handle.add_segment(
            "t",
            build_segment(
                schema,
                {
                    "city": rng.choice(["sf", "nyc", "la"], rows).astype(object),
                    "v": rng.integers(0, 100, rows),
                    "ts": 1_700_000_000_000
                    + rng.integers(0, 86_400_000, rows).astype(np.int64),
                },
                f"seg{i}",
                output_dir=os.path.join(tmp, "build", f"seg{i}"),
            ),
        )

    # hot standby boots AFTER the load so bootstrap + incremental tail both run
    standby = Coordinator(
        replication=2,
        meta_dir=os.path.join(tmp, "meta"),
        deep_store=os.path.join(tmp, "deep"),
        node_id="coord-b",
        standby=True,
        lease_ttl_s=ttl_s,
        clock=clock,
    )
    plan.attach_coordinator(standby)
    handle.add_candidate(standby)

    broker = Broker(handle)
    warm = broker.query(sql)
    expected.extend(list(warm.rows))
    old_epoch = leader.election.epoch

    # ---- the drill ----------------------------------------------------
    f0 = METRICS.counter("coordinator.fencedAppends").value
    plan.pause_leader("coord-a")
    t0 = sim[0]
    in_blackout[0] = True
    handle.heartbeat("server0")  # parks, ticks the election, lands on coord-b
    in_blackout[0] = False
    blackout_ms = (sim[0] - t0) * 1000.0

    # ---- split-brain fence proof --------------------------------------
    plan.resume_leader("coord-a")
    fenced = False
    try:
        leader.drop_table("t")  # old epoch writing directly: must fence
    except FencedEpochError:
        fenced = True
    post = broker.query(sql)  # routed via the adopted new leader's view
    n_probes = probes["ok"] + probes["bad"]
    return {
        "lease_ttl_s": ttl_s,
        "blackout_ms": round(blackout_ms, 3),
        "replay_to_tip_ms": round(standby.last_promote_ms, 3),
        "data_plane": {
            "queries_during_blackout": n_probes,
            "ok": probes["ok"],
            "success_rate": round(probes["ok"] / n_probes, 3) if n_probes else None,
        },
        "old_epoch": old_epoch,
        "new_epoch": standby.election.epoch,
        "new_leader": standby.node_id,
        "old_leader_fenced": fenced,
        "fenced_appends": METRICS.counter("coordinator.fencedAppends").value - f0,
        "post_failover_query_ok": list(post.rows) == expected,
    }


def _autopilot_overload_bench() -> dict:
    """Closed-loop autopilot vs a grid of static knob settings (ISSUE 18):
    mixed-tenant load (scans + group-bys on `hot`, funnels on `events`)
    offered at 3x estimated capacity by paced client threads, with one of
    two replicas carrying a seeded latency jitter (the r15 gray-fault
    model).  Every leg runs the same admission ceiling and the same fault;
    only the knob settings differ — static legs pin KnobRegistry overrides
    up front, the autopilot leg starts at env defaults and lets the
    controller move one knob per tick.  Reports admitted p99 per leg,
    `autopilot_admitted_p99_ms` (lower-is-better in the `cli perf --check`
    gate), `autopilot_vs_best_static`, and the knob-change count against
    the controller's own oscillation bound."""
    import threading

    from pinot_tpu.cluster.admission import (
        AdmissionController,
        QueryKilledError,
        ReservationError,
        TooManyRequestsError,
        estimate_query_cost,
    )
    from pinot_tpu.cluster import autopilot as ap_mod
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.coordinator import Coordinator
    from pinot_tpu.cluster.faults import FaultPlan
    from pinot_tpu.cluster.server import ServerInstance
    from pinot_tpu.segment.builder import build_segment
    from pinot_tpu.spi.config import SegmentsConfig, TableConfig
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.sql.parser import parse_query
    from pinot_tpu.utils import perf

    rows = int(os.environ.get("BENCH_AUTOPILOT_ROWS", 5_000))
    n_clients = int(os.environ.get("BENCH_AUTOPILOT_CLIENTS", 12))
    # two-phase legs: an unmeasured warm-up (the closed loop converges, the
    # static legs burn the identical schedule) then the measured window
    reqs_warm = int(os.environ.get("BENCH_AUTOPILOT_WARM_REQS", 48))
    # at 3x overload most offered requests shed, so the admitted-p99 order
    # statistic needs a wide measured window to settle (legs are seconds each)
    reqs_meas = int(os.environ.get("BENCH_AUTOPILOT_REQS", 160))
    reqs = reqs_warm + reqs_meas
    overload_x = 3.0

    rng = np.random.default_rng(11)
    coord = Coordinator(replication=2)
    for i in range(2):
        coord.register_server(ServerInstance(f"server{i}"))
    hot = Schema(
        "hot",
        [
            FieldSpec("city", DataType.STRING),
            FieldSpec("v", DataType.LONG, role=FieldRole.METRIC),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )
    coord.add_table(hot, TableConfig(name="hot", segments=SegmentsConfig(time_column="ts")))
    events = Schema(
        "events",
        [
            FieldSpec("uid", DataType.LONG),
            FieldSpec("url", DataType.STRING),
            FieldSpec("ts", DataType.TIMESTAMP, role=FieldRole.DATE_TIME),
        ],
    )
    coord.add_table(
        events, TableConfig(name="events", segments=SegmentsConfig(time_column="ts"))
    )
    for i in range(4):
        coord.add_segment(
            "hot",
            build_segment(
                hot,
                {
                    "city": rng.choice(["sf", "nyc", "la"], rows).astype(object),
                    "v": rng.integers(0, 100, rows),
                    "ts": 1_700_000_000_000 + rng.integers(0, 86_400_000, rows).astype(np.int64),
                },
                f"hot{i}",
            ),
        )
        coord.add_segment(
            "events",
            build_segment(
                events,
                {
                    "uid": rng.integers(0, 300, rows).astype(np.int64),
                    "url": rng.choice(["/home", "/product", "/cart"], rows).astype(object),
                    "ts": 1_700_000_000_000 + rng.integers(0, 86_400_000, rows).astype(np.int64),
                },
                f"ev{i}",
            ),
        )
    broker = Broker(coord)
    broker.health.brownout_factor = float("inf")  # isolate knobs from routing-away

    shapes = [
        lambda i: (
            "SELECT city, COUNT(*), SUM(v) FROM hot "
            f"WHERE v < {50 + i % 40} GROUP BY city ORDER BY city"
        ),
        lambda i: f"SELECT COUNT(*), MAX(v) FROM hot WHERE v > {i % 40}",
        lambda i: (
            "SELECT FUNNELCOUNT(STEPS(url = '/home', url = '/cart'), "
            f"CORRELATEBY(uid)) FROM events WHERE uid >= {i % 20}"
        ),
    ]

    def sql_at(i: int) -> str:
        return shapes[i % len(shapes)](i)

    for i in range(12):  # warm every shape: parse, plan, compile, hedge windows
        broker.query(sql_at(i))

    # ---- capacity + gray fault calibration ----------------------------
    cal = []
    for i in range(30):
        t0 = time.perf_counter()
        broker.query(sql_at(i))
        cal.append((time.perf_counter() - t0) * 1000)
    med_ms = float(np.median(cal))
    capacity_qps = 1000.0 / med_ms
    slow_ms = round(4.0 * max(0.5, med_ms), 3)
    FaultPlan(seed=17).jitter("server0", base_ms=slow_ms, sigma=0.3).attach(coord)

    unit_cost = estimate_query_cost(
        parse_query(shapes[0](0)), coord.tables["hot"].segment_meta.values()
    ).units
    rate_units = capacity_qps * unit_cost
    # the static env ceilings every leg (and the registry clamps) run under:
    # hedging on with a fat budget, admission refill at estimated capacity
    env_ceilings = {
        "PINOT_TPU_HEDGE_BUDGET_PCT": "60",
        "PINOT_TPU_ADMISSION_RATE": f"{rate_units:.4f}",
    }
    saved_env = {k: os.environ.get(k) for k in env_ceilings}
    os.environ.update(env_ceilings)
    broker.hedge.enabled_default = True
    # achievable target under the fault model: one un-hedged scatter leg
    # rides the slow replica, so the admitted tail floors near 2x its
    # jitter base — an SLO below that saturates the ladder instead of
    # letting the loop settle on the cheapest config that meets it
    slo_ms = round(2.0 * slow_ms, 3)
    interval_s = n_clients / (overload_x * capacity_qps)  # per-client pacing

    def run_leg(overrides) -> dict:
        ap_mod.reset_knobs()
        if overrides:
            ap_mod.knobs().set_many(overrides, who="static-config")
        perf.PERF_LEDGER.reset()
        adm = AdmissionController(
            rate_units_per_s=rate_units,
            burst_units=2 * unit_cost,
            max_queue=0,
            knob="admission_rate",
        )
        broker.governor.admission = adm
        pilot = None
        if overrides is None:  # the closed-loop leg
            # 0.1 s tick: fast enough to converge well inside the warm-up
            # phase, slow enough that the controller's own ledger snapshots
            # don't tax the saturated host during the measured window
            pilot = ap_mod.Autopilot(
                governor=broker.governor, slo_ms=slo_ms, tick_s=0.1
            )
            pilot.start()
        lats, lock = [], threading.Lock()
        counts = {"admitted": 0, "shed": 0, "killed": 0}
        barrier = threading.Barrier(n_clients + 1)

        def client(cid):
            barrier.wait()
            for r in range(reqs):
                time.sleep(interval_s)
                measured = r >= reqs_warm
                t0 = time.perf_counter()
                try:
                    broker.query(sql_at(cid * reqs + r))
                except TooManyRequestsError:
                    if measured:
                        with lock:
                            counts["shed"] += 1
                except (QueryKilledError, ReservationError):
                    if measured:
                        with lock:
                            counts["killed"] += 1
                else:
                    if measured:
                        with lock:
                            counts["admitted"] += 1
                            lats.append((time.perf_counter() - t0) * 1000)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        barrier.wait()
        for t in threads:
            t.join()
        broker.hedge_drain()
        leg = {
            **counts,
            "admitted_p99_ms": (
                round(float(np.percentile(lats, 99)), 3) if lats else None
            ),
            "admitted_p50_ms": (
                round(float(np.percentile(lats, 50)), 3) if lats else None
            ),
        }
        if pilot is not None:
            pilot.stop()
            snap = pilot.snapshot()
            moves = [
                d for d in snap["decisions"] if d["action"] in ("degrade", "recover")
            ]
            win, cap = snap["changeBound"]["windowTicks"], snap["changeBound"]["maxChanges"]
            worst = 0
            ticks = [d["tick"] for d in moves]
            for t in ticks:
                worst = max(worst, len([m for m in ticks if t - win < m <= t]))
            assert worst <= cap, f"oscillation bound violated: {worst} moves/{win} ticks"
            leg["knob_changes"] = snap["knobChanges"]
            leg["ladder_walks"] = snap["ladderWalks"]
            leg["max_changes_per_window"] = worst
            leg["change_bound"] = cap
            leg["final_knobs"] = {
                n: k["value"]
                for n, k in snap["knobs"].items()
                if k["overridden"]
            }
        return leg

    # admitted-p99 under a shed-heavy window is a tail order statistic riding
    # on the seeded jitter's random draw — gate the median repeat, not one
    # draw. Repeats are interleaved round-robin across configs (not config by
    # config) so slow host drift over the section lands on every config
    # equally instead of taxing whichever leg happens to run last.
    n_rep = int(os.environ.get("BENCH_AUTOPILOT_REPEATS", 3))

    def median_leg(runs) -> dict:
        runs = sorted(runs, key=lambda leg: leg["admitted_p99_ms"] or float("inf"))
        med = runs[len(runs) // 2]
        med["admitted_p99_ms_runs"] = [r["admitted_p99_ms"] for r in runs]
        return med

    try:
        static_grid = {
            "default": {},  # env ceilings as-is: hedge 60%, full refill rate
            "no_hedge": {"hedge_budget_pct": 0.0},
            "half_rate": {"admission_rate": 0.5 * rate_units},
            # the degradation ladder's floor: if the closed loop saturates,
            # this is its static twin — the grid always contains whatever
            # config the controller converges to
            "floor": {
                "hedge_budget_pct": 0.0,
                "batch_wait_ms": 8.0,
                "pipeline_depth": 1,
                "staging_depth": 1,
                "admission_rate": 0.25 * rate_units,
                "degrade_level": 3,
            },
        }
        order = list(static_grid.items()) + [("autopilot", None)]
        rep_runs = {name: [] for name, _ in order}
        for _ in range(n_rep):
            for name, ov in order:
                rep_runs[name].append(run_leg(ov))
        statics = {name: median_leg(rep_runs[name]) for name in static_grid}
        pilot_leg = median_leg(rep_runs["autopilot"])
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        ap_mod.reset_knobs()
        broker.governor.admission = AdmissionController()  # back to permissive

    best_name, best = min(
        statics.items(), key=lambda kv: kv[1]["admitted_p99_ms"] or float("inf")
    )
    vs_best = (
        round(pilot_leg["admitted_p99_ms"] / best["admitted_p99_ms"], 3)
        if pilot_leg["admitted_p99_ms"] and best["admitted_p99_ms"]
        else None
    )
    return {
        "capacity_qps_est": round(capacity_qps, 1),
        "offered_x": overload_x,
        "slow_replica_ms": slow_ms,
        "slo_ms": slo_ms,
        "clients": n_clients,
        "warmup_requests_per_client": reqs_warm,
        "measured_requests_per_client": reqs_meas,
        "repeats": n_rep,
        "static": statics,
        "best_static": best_name,
        "autopilot": pilot_leg,
        "autopilot_vs_best_static": vs_best,
    }


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pinot_tpu import ops
    from pinot_tpu.parallel.engine import DistributedEngine
    from pinot_tpu.parallel.stacked import StackedTable
    from pinot_tpu.spi.config import IndexingConfig, TableConfig
    from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema
    from pinot_tpu.sql.parser import parse_query

    rng = np.random.default_rng(42)
    n = N_ROWS
    schema = Schema(
        "lineorder",
        [
            FieldSpec("lo_orderdate", DataType.INT),
            FieldSpec("lo_quantity", DataType.INT),
            FieldSpec("lo_discount", DataType.INT),
            FieldSpec("lo_revenue", DataType.LONG, role=FieldRole.METRIC),
        ],
    )
    data = {
        "lo_orderdate": (19920101 + rng.integers(0, 2406, n)).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
        # cardinality 11 -> 4-bit lanes: the scan-bound section's packed
        # column (8 codes per uint32 word)
        "lo_discount": rng.integers(0, 11, n).astype(np.int32),
        "lo_revenue": rng.integers(100, 1_000_000, n).astype(np.int64),
    }

    cfg = TableConfig(
        "lineorder",
        indexing=IndexingConfig(range_index_columns=["lo_quantity"]),
    )
    ndev = len(jax.devices())
    stacked = StackedTable.build(schema, data, num_shards=ndev, table_config=cfg)
    engine = DistributedEngine()
    engine.register_table("lineorder", stacked)

    sql = (
        "SELECT lo_orderdate, SUM(lo_revenue) FROM lineorder "
        "WHERE lo_quantity < 25 GROUP BY lo_orderdate LIMIT 2500"
    )
    ctx = parse_query(sql)

    r = engine.execute(ctx)  # full-path warm-up: compile + correctness
    assert r.rows, "bench query returned nothing"
    index_uses = list(r.stats.filter_index_uses)
    assert index_uses, "bench filter must ride the range index"

    # ---- end-to-end timing + latency distribution ---------------------
    # execute() feeds the dist.queryLatency histogram; resetting first makes
    # the p50/p95/p99 below cover exactly these runs
    from pinot_tpu.utils.metrics import METRICS

    METRICS.reset()
    e2e_ts = []
    for _ in range(7):
        t0 = time.perf_counter()
        engine.execute(ctx)
        e2e_ts.append(time.perf_counter() - t0)
    e2e = float(np.min(e2e_ts))
    lat = METRICS.snapshot()["histograms"]["dist.queryLatency"]

    # ---- distinct-literal sweep ---------------------------------------
    # Round-6 tentpole proof: N same-shape queries differing only in the
    # filter literal must share ONE compiled kernel — the plan cache keys
    # on the shape fingerprint (literals canonicalized to parameter slots)
    # and the literal rides in as a device argument.  Before
    # parameterization each literal was a fresh trace+compile.
    from pinot_tpu.analysis.compile_audit import DIST_AUDIT

    DIST_AUDIT.reset()
    sweep_n = int(os.environ.get("BENCH_SWEEP", 20))
    sweep_ts = []
    for i in range(sweep_n):
        q = parse_query(
            "SELECT lo_orderdate, SUM(lo_revenue) FROM lineorder "
            f"WHERE lo_quantity < {5 + (i % 40)} GROUP BY lo_orderdate LIMIT 2500"
        )
        t0 = time.perf_counter()
        engine.execute(q)
        sweep_ts.append(time.perf_counter() - t0)
    sweep_compiles = sum(DIST_AUDIT.counts().values())
    # snapshot here so the audit covers exactly the sweep since reset():
    # cold = first trace per shape, warm_recompiles = re-traces of a seen
    # shape (a literal leaking into the plan key shows up here first)
    plan_cache = DIST_AUDIT.summary()
    sweep = {
        "queries": sweep_n,
        "compiles": sweep_compiles,
        "cache_hit_rate": round((sweep_n - sweep_compiles) / sweep_n, 3),
        "warm_p50_ms": round(float(np.median(sweep_ts)) * 1000, 3),
        "warm_p50_rows_per_sec": round(n / float(np.median(sweep_ts)), 1),
    }

    # ---- per-stage trace summary --------------------------------------
    # one traced run (separate plan-cache entry: options ride the
    # fingerprint); per-stage ms aggregated by span base name
    from pinot_tpu.query.analyze import _span_ms_index

    traced = engine.execute(parse_query("SET trace = true; " + sql))
    stage_ms = {
        k: round(v, 3)
        for k, v in sorted(_span_ms_index(traced.stats.trace).items())
        if ":" not in k  # per-batch dispatch:N spans already sum under 'dispatch'
    }

    # ---- marginal kernel timing ---------------------------------------
    # Macro-batch launches (round 5): the engine splits the doc axis so one
    # launch's while-loop capture copy never exceeds the HBM budget — the
    # fix that fits 1.07B rows on a single chip.  All batches share shapes,
    # so the K-loop compiles once and runs per batch; timings sum batches.
    plan = engine._plan(ctx, stacked)
    batches = engine.device_batches(plan, stacked)
    # per-iteration param wobble so the loop body depends on the index — no
    # loop-invariant hoisting.  The indexed filter ships bitmap words: XOR
    # the first word with (i % 2), flipping one doc's membership.
    bits_key = next(iter(plan.row_sharded_params), None)
    hi_key = next((k for k in plan.params if k.endswith(".hi")), None)

    def make_loop(k_iters: int):
        def run(cols, params):
            def body(i, acc):
                p = dict(params)
                if bits_key is not None:
                    w = params[bits_key]
                    p[bits_key] = w.at[..., 0].set(w[..., 0] ^ (i % 2).astype(jnp.uint32))
                elif hi_key is not None:
                    p[hi_key] = params[hi_key] - (i % 2).astype(jnp.int32)
                presence, partials = plan.fn(cols, p)
                leaves = jax.tree_util.tree_leaves((presence, partials))
                return acc + sum(jnp.sum(l).astype(jnp.float64) for l in leaves)

            return lax.fori_loop(0, k_iters, body, jnp.float64(0))

        fn = jax.jit(run)
        for cols, params in batches:  # compile + first transfer
            jax.device_get(fn(cols, params))
        return fn

    def time_once(fn) -> float:
        t0 = time.perf_counter()
        for cols, params in batches:
            jax.device_get(fn(cols, params))
        return time.perf_counter() - t0

    fn_1 = make_loop(1)
    fn_k = make_loop(K_ITERS)

    # Round-5 hardening (VERDICT r4 #1): a single (t_1, t_K) pair is not
    # robust to relay contention — one slow t_K understated r4 by 21x.
    # Take the median slope over >=3 interleaved pairs (each timing the min
    # of 3 runs), cross-check against the amortized lower bound
    # n*K/min(t_K) — which cannot be corrupted by subtraction noise — and
    # re-measure when the two disagree by >25%.  Report the max of the two
    # (the amortized figure still *includes* fixed dispatch overhead, so it
    # is a strict lower bound on marginal throughput), plus run variance.
    def measure_pair():
        t1 = min(time_once(fn_1) for _ in range(3))
        tk = min(time_once(fn_k) for _ in range(3))
        return t1, tk

    pairs = [measure_pair() for _ in range(3)]

    def summarize(ps):
        # a contended t_1 can exceed t_K, making the slope non-positive —
        # such pairs are invalid samples, not data; drop them rather than
        # clamp (a clamp would publish an absurdly HIGH record).
        slopes = [(tk - t1) / (K_ITERS - 1) for t1, tk in ps]
        valid = [s for s in slopes if s > 0]
        min_tk = min(tk for _, tk in ps)
        amortized = n * K_ITERS / min_tk  # lower bound, subtraction-free
        if not valid:
            return None, 0.0, amortized, [], len(slopes)
        per_query = float(np.median(valid))
        return per_query, n / per_query, amortized, valid, len(slopes) - len(valid)

    per_query, marg, amortized, slopes, n_invalid = summarize(pairs)
    remeasured = 0
    while (marg < 0.75 * amortized or not slopes) and remeasured < 2:
        # slope estimate inconsistent with its own lower bound (or no valid
        # pair at all): contention hit a timing run.  Gather more pairs.
        pairs.extend(measure_pair() for _ in range(2))
        per_query, marg, amortized, slopes, n_invalid = summarize(pairs)
        remeasured += 1

    # marg can only be trusted above the floor; with no valid slopes the
    # subtraction-free amortized floor IS the measurement.
    rows_per_sec = max(marg, amortized)
    spread = (
        (max(slopes) - min(slopes)) / float(np.median(slopes)) if slopes else -1.0
    )

    # Physical scan bandwidth: bytes the kernel actually streams per row —
    # bit-packed dict columns at code_bits/8 (the uint32 lane words are
    # what ships; perf.analytic_bytes_per_row reads the stored lane width),
    # null bitmaps at 1 byte/row, plus one uint32 per 32 rows for each
    # row-sharded index-bitmap param.
    from pinot_tpu.utils import perf

    bytes_per_row = perf.analytic_bytes_per_row(
        (stacked.column(name) for name in plan.needed_columns),
        bitmap_params=len(plan.row_sharded_params),
    )
    # Logical consumption bandwidth: decoded widths of every column the
    # QUERY references — including the index-answered filter column the
    # kernel never touches.  effective_bytes_per_sec is rows/s times THIS
    # figure: how fast the engine chews logical data, the row-store
    # equivalent a user compares engines by.  The physical figure above
    # (smaller, post-packing) is what the roofline divides by.
    _DECODED_WIDTH = {"INT": 4, "LONG": 8, "FLOAT": 4, "DOUBLE": 8}
    logical_bytes_per_row = sum(
        _DECODED_WIDTH[f.data_type.value] for f in schema.fields if f.name != "lo_discount"
    ) + len(plan.row_sharded_params) * 4 / 32

    # ---- roofline reconciliation (observatory r6) ---------------------
    # Two byte models for the same kernel: the analytic packed-storage
    # estimate above vs XLA's own cost_analysis() on the lowered plan
    # (force="xla" — on CPU the serving path skips the extra lowering, but
    # the bench pays it once to reconcile the models).  The roofline %
    # divides the PACKED physical figure into the device peak;
    # cost_analysis stays reported as the reconciliation cross-check.

    batch_rows = getattr(plan, "batch_docs", 0) or n
    xla_cost = perf.capture_cost(
        plan.fn,
        batches[0],
        perf.analytic_cost(
            batch_rows,
            bytes_per_row,
            kind=plan.kind,
            num_groups=plan.num_groups,
            num_entries=len(plan.aggs),
        ),
        force="xla",
    )
    cost_bpr = xla_cost.bytes_accessed / batch_rows if xla_cost.source == "xla" else None
    peak_bps = perf.peak_hbm_bytes_per_sec()
    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:
        device_kind = "unknown"
    roofline = {
        "device_kind": device_kind,
        "peak_hbm_bytes_per_sec": peak_bps,
        "source": xla_cost.source,  # "xla" when cost_analysis answered, else "analytic"
        "analytic_bytes_per_row": round(bytes_per_row, 3),
        "cost_analysis_bytes_per_row": round(cost_bpr, 3) if cost_bpr is not None else None,
        # >1 means XLA sees more traffic than the packed-storage model
        # (widening copies, bitmap word reads); the gap is the reconciliation
        "bytes_model_ratio": round(cost_bpr / bytes_per_row, 3) if cost_bpr and bytes_per_row else None,
        "cost_bytes_per_sec": round(rows_per_sec * cost_bpr, 1) if cost_bpr is not None else None,
        # per-section achieved-vs-peak %: marginal kernel, e2e, warm sweep —
        # all from PACKED physical bytes (bit-packed forward index widths)
        "kernel_roofline_pct": round(100.0 * rows_per_sec * bytes_per_row / peak_bps, 3),
        "e2e_roofline_pct": round(100.0 * (n / e2e) * bytes_per_row / peak_bps, 3),
        "warm_p50_roofline_pct": round(
            100.0 * sweep["warm_p50_rows_per_sec"] * bytes_per_row / peak_bps, 3
        ),
    }

    # ---- scan-bound / agg-bound sections (packed forward indexes) -----
    # scan_bound: low-selectivity predicate over the UNINDEXED 4-bit
    # lo_discount column — the kernel streams packed lane words and
    # unpacks in-register, so throughput is filter-scan-limited.
    # agg_bound: no filter, group-by-heavy multi-agg — throughput is
    # accumulate-limited.  Both report achieved rows/s and roofline %
    # from packed physical bytes; both are gated (perf.GATE_METRICS).
    def _section(sql_s: str, warm_iters: int = 5) -> dict:
        ctx_s = parse_query(sql_s)
        res_s = engine.execute(ctx_s)  # compile + correctness
        assert res_s.rows, f"section query returned nothing: {sql_s}"
        ts = []
        for _ in range(warm_iters):
            t0 = time.perf_counter()
            engine.execute(ctx_s)
            ts.append(time.perf_counter() - t0)
        sec = float(np.min(ts))
        plan_s = engine._plan(ctx_s, stacked)
        pbpr = perf.analytic_bytes_per_row(
            (stacked.column(nm) for nm in plan_s.needed_columns),
            bitmap_params=len(plan_s.row_sharded_params),
        )
        rps = n / sec
        return {
            "sql": sql_s,
            "rows_per_sec": round(rps, 1),
            "packed_bytes_per_row": round(pbpr, 3),
            "bytes_per_sec": round(rps * pbpr, 1),
            "roofline_pct": round(100.0 * rps * pbpr / peak_bps, 3),
        }

    scan_bound_sql = "SELECT COUNT(*) FROM lineorder WHERE lo_discount = 7"
    agg_bound_sql = (
        "SELECT lo_orderdate, COUNT(*), SUM(lo_revenue), AVG(lo_quantity) "
        "FROM lineorder GROUP BY lo_orderdate LIMIT 2500"
    )
    scan_bound = _section(scan_bound_sql)
    agg_bound = _section(agg_bound_sql)

    # ---- packed-parity: packed vs unpacked execution is bit-exact -----
    # The same table with packing metadata stripped rides the raw unpacked
    # path end to end; every query must return IDENTICAL rows — cold and
    # warm, batched (small launch_bytes forces macro-batching) and not.
    import dataclasses as _dc

    plain_cols = {
        nm: _dc.replace(c, code_bits=None, packed=None)
        for nm, c in stacked.columns.items()
    }
    plain = StackedTable(
        stacked.schema, plain_cols, stacked.valid, stacked.num_docs,
        indexes=stacked.indexes,
    )
    parity = {"bit_exact": True, "cases": 0}
    parity_sqls = [sql, scan_bound_sql, agg_bound_sql]
    for lb in (None, 8 << 20):
        eng_p = DistributedEngine(launch_bytes=lb) if lb else DistributedEngine()
        eng_p.register_table("lineorder", stacked)
        eng_u = DistributedEngine(launch_bytes=lb) if lb else DistributedEngine()
        eng_u.register_table("lineorder", plain)
        for sql_s in parity_sqls:
            q = parse_query(sql_s)
            cold_p = [tuple(r) for r in eng_p.execute(q).rows]
            cold_u = [tuple(r) for r in eng_u.execute(q).rows]
            warm_p = [tuple(r) for r in eng_p.execute(q).rows]
            warm_u = [tuple(r) for r in eng_u.execute(q).rows]
            parity["cases"] += 1
            if not (cold_p == cold_u == warm_p == warm_u):
                parity["bit_exact"] = False
                parity.setdefault("mismatches", []).append(
                    {"sql": sql_s, "batched": bool(lb)}
                )
    assert parity["bit_exact"], f"packed/unpacked parity FAILED: {parity}"

    report = {
        "metric": "ssb_groupby_rows_scanned_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "vs_baseline": round(rows_per_sec / JAVA_SERVER_ROWS_PER_SEC, 3),
        "value_marginal": round(marg, 1),
        "value_amortized_floor": round(amortized, 1),
        "run_variance": round(spread, 4),
        "timing_pairs": [[round(a, 4), round(b, 4)] for a, b in pairs],
        "invalid_pairs": n_invalid,
        "remeasure_rounds": remeasured,
        "value_e2e": round(n / e2e, 1),
        "e2e_seconds": round(e2e, 4),
        "latency_ms": {
            "count": lat["count"],
            "p50": round(lat["p50Ms"], 3),
            "p95": round(lat["p95Ms"], 3),
            "p99": round(lat["p99Ms"], 3),
            "mean": round(lat["meanMs"], 3),
            "max": round(lat["maxMs"], 3),
        },
        "trace_stage_ms": stage_ms,
        "distinct_literal_sweep": sweep,
        "plan_cache": {
            "hits": plan_cache["hits"],
            "cold_compiles": plan_cache["cold_compiles"],
            "warm_recompiles": plan_cache["warm_recompiles"],
            "hit_rate": round(plan_cache["hit_rate"], 3),
        },
        "rows": n,
        "filter_index_uses": index_uses,
        "cpu_proxy_rows_per_sec": round(_cpu_proxy(), 1),
        "baseline_denominator": JAVA_SERVER_ROWS_PER_SEC,
        "backend": ops.scan_backend(),
        # logical (decoded-width) model: how fast the engine consumes the
        # query's data; the packed physical figure drives the roofline
        "effective_bytes_per_sec": round(rows_per_sec * logical_bytes_per_row, 1),
        "logical_bytes_per_row": round(logical_bytes_per_row, 3),
        "physical_bytes_per_sec": round(rows_per_sec * bytes_per_row, 1),
        "scan_bound": scan_bound,
        "agg_bound": agg_bound,
        "packed_parity": parity,
        "roofline": roofline,
        "overload": _overload_bench(),
        "tail_latency": _tail_latency_bench(),
        "concurrent_qps": _concurrent_qps_bench(),
        "mesh_scaling": _mesh_scaling_bench(),
        "working_set_sweep": _working_set_sweep(),
        "failover": _failover_bench(),
        "autopilot_overload": _autopilot_overload_bench(),
    }
    print(json.dumps(report))

    # ---- bench history (regression gate input) ------------------------
    # One flat line per run; `cli perf --check` compares the newest line
    # against the pinned BENCH_BASELINE.json.  PINOT_TPU_BENCH_HISTORY=0
    # disables; any other value overrides the path.
    history = os.environ.get(
        "PINOT_TPU_BENCH_HISTORY",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_history.jsonl"),
    )
    if history != "0":
        rec = perf.bench_record(report)
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        perf.append_bench_history(history, rec)


if __name__ == "__main__":
    main()
