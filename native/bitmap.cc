// Roaring-style compressed bitmap codec.
//
// Reference parity: the RoaringBitmap dependency Pinot uses for inverted /
// range / json indexes and validDocIds (SURVEY.md 2.4) — the one place the
// reference's "native" capability is a library, re-implemented here as the
// framework's own C++ runtime component.
//
// Format (little-endian):
//   u32 n_containers
//   per container:
//     u32 key        (chunk index: docId >> 16)
//     u8  type       (0 = sorted u16 array, 1 = 8KiB bitmap)
//     u32 count      (cardinality within the container)
//     payload        (array: count * u16; bitmap: 8192 bytes)
//
// Containers switch to bitmaps above ARRAY_MAX entries — the classic
// Roaring threshold where 2-byte entries stop beating the fixed 8KiB.

#include <cstdint>
#include <cstring>

static const int64_t CHUNK = 65536;
static const int64_t ARRAY_MAX = 4096;
static const int64_t BITMAP_BYTES = 8192;

struct Writer {
  uint8_t* out;
  int64_t cap;
  int64_t pos;
  bool ok;
  void put(const void* src, int64_t n) {
    if (!ok || pos + n > cap) { ok = false; return; }
    memcpy(out + pos, src, n);
    pos += n;
  }
  template <typename T> void put1(T v) { put(&v, sizeof(T)); }
};

extern "C" {

// Upper bound for allocating the output buffer.
int64_t rb_max_compressed_size(int64_t n_docs) {
  int64_t containers = n_docs / ARRAY_MAX + 2;
  return 4 + containers * (9 + BITMAP_BYTES);
}

// docs: sorted ascending, distinct. Returns bytes written, or -1 on overflow.
int64_t rb_compress(const uint32_t* docs, int64_t n, uint8_t* out, int64_t cap) {
  Writer w{out, cap, 0, true};
  w.put1<uint32_t>(0);  // container count backpatched below
  uint32_t n_containers = 0;
  int64_t i = 0;
  while (i < n && w.ok) {
    uint32_t key = docs[i] >> 16;
    int64_t j = i;
    while (j < n && (docs[j] >> 16) == key) j++;
    int64_t count = j - i;
    w.put1<uint32_t>(key);
    if (count <= ARRAY_MAX) {
      w.put1<uint8_t>(0);
      w.put1<uint32_t>((uint32_t)count);
      for (int64_t k = i; k < j; k++) w.put1<uint16_t>((uint16_t)(docs[k] & 0xFFFF));
    } else {
      w.put1<uint8_t>(1);
      w.put1<uint32_t>((uint32_t)count);
      if (w.ok && w.pos + BITMAP_BYTES <= cap) {
        uint8_t* bm = out + w.pos;
        memset(bm, 0, BITMAP_BYTES);
        for (int64_t k = i; k < j; k++) {
          uint32_t low = docs[k] & 0xFFFF;
          bm[low >> 3] |= (uint8_t)(1u << (low & 7));
        }
        w.pos += BITMAP_BYTES;
      } else {
        w.ok = false;
      }
    }
    n_containers++;
    i = j;
  }
  if (!w.ok) return -1;
  memcpy(out, &n_containers, 4);
  return w.pos;
}

}  // extern "C"

struct Reader {
  const uint8_t* buf;
  int64_t len;
  int64_t pos;
  bool ok;
  void get(void* dst, int64_t n) {
    if (!ok || pos + n > len) { ok = false; return; }
    memcpy(dst, buf + pos, n);
    pos += n;
  }
  template <typename T> T get1() { T v{}; get(&v, sizeof(T)); return v; }
  const uint8_t* skip(int64_t n) {
    if (!ok || pos + n > len) { ok = false; return nullptr; }
    const uint8_t* p = buf + pos;
    pos += n;
    return p;
  }
};

extern "C" {

int64_t rb_cardinality(const uint8_t* buf, int64_t len) {
  Reader r{buf, len, 0, true};
  uint32_t nc = r.get1<uint32_t>();
  int64_t total = 0;
  for (uint32_t c = 0; c < nc && r.ok; c++) {
    r.get1<uint32_t>();  // key
    uint8_t type = r.get1<uint8_t>();
    uint32_t count = r.get1<uint32_t>();
    total += count;
    r.skip(type == 0 ? (int64_t)count * 2 : BITMAP_BYTES);
  }
  return r.ok ? total : -1;
}

// OR the compressed bitmap into dense u32 words (bit d of word d>>5).
// Returns the bitmap's cardinality, or -1 on corruption/overflow.
int64_t rb_decompress(const uint8_t* buf, int64_t len, uint32_t* words, int64_t n_words) {
  Reader r{buf, len, 0, true};
  uint32_t nc = r.get1<uint32_t>();
  int64_t total = 0;
  for (uint32_t c = 0; c < nc && r.ok; c++) {
    uint32_t key = r.get1<uint32_t>();
    uint8_t type = r.get1<uint8_t>();
    uint32_t count = r.get1<uint32_t>();
    int64_t base = (int64_t)key * CHUNK;
    total += count;
    if (type == 0) {
      for (uint32_t k = 0; k < count && r.ok; k++) {
        uint16_t low = r.get1<uint16_t>();
        int64_t doc = base + low;
        if ((doc >> 5) >= n_words) return -1;
        words[doc >> 5] |= 1u << (doc & 31);
      }
    } else {
      const uint8_t* bm = r.skip(BITMAP_BYTES);
      if (!r.ok) return -1;
      int64_t w0 = base >> 5;
      const uint32_t* src = (const uint32_t*)bm;
      // the words buffer may end mid-chunk (n_docs not a chunk multiple);
      // bits past it must be absent or the data claims impossible docs
      int64_t avail = n_words - w0;
      if (avail < 0) avail = 0;
      int64_t copy = avail < CHUNK / 32 ? avail : CHUNK / 32;
      for (int64_t k = 0; k < copy; k++) words[w0 + k] |= src[k];
      for (int64_t k = copy; k < CHUNK / 32; k++)
        if (src[k]) return -1;
    }
  }
  return r.ok ? total : -1;
}

}  // extern "C"
