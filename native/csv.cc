// CSV -> columnar field-offset parser (the data-loader hot loop).
//
// Reference parity: pinot-plugins/pinot-input-format CSV record reader —
// the per-row Java parse loop becomes one C++ scan emitting field offset
// pairs; Python slices columns out of the original buffer with numpy, so
// the per-field Python work disappears.
//
// RFC-4180-ish: quoted fields ("" escapes a quote, delimiters/newlines
// allowed inside quotes), \n / \r\n row terminators.

#include <cstdint>

extern "C" {

// Count data rows (quoted newlines don't split rows). A trailing unterminated
// line counts as a row.
int64_t csv_count_rows(const char* data, int64_t len) {
  int64_t rows = 0;
  bool in_quotes = false;
  bool row_has_data = false;
  for (int64_t i = 0; i < len; i++) {
    char ch = data[i];
    if (in_quotes) {
      if (ch == '"') {
        if (i + 1 < len && data[i + 1] == '"') i++;
        else in_quotes = false;
      }
      row_has_data = true;
    } else if (ch == '"') {
      in_quotes = true;
      row_has_data = true;
    } else if (ch == '\n') {
      if (row_has_data) rows++;
      row_has_data = false;
    } else if (ch != '\r') {
      row_has_data = true;
    }
  }
  if (row_has_data) rows++;
  return rows;
}

// Emit (start, end) byte offsets for every field, row-major, ncols per row.
// quoted[f] = 1 marks fields needing quote-unescaping in Python (rare path).
// Returns rows parsed; -1 if a row has the wrong arity or buffers overflow.
int64_t csv_parse(const char* data, int64_t len, char delim, int64_t ncols,
                  int64_t* starts, int64_t* ends, uint8_t* quoted,
                  int64_t max_fields) {
  int64_t row = 0, col = 0, f = 0;
  int64_t field_start = 0;
  bool in_quotes = false, was_quoted = false;
  int64_t i = 0;

  auto end_field = [&](int64_t end_pos) -> bool {
    if (f >= max_fields || col >= ncols) return false;
    starts[f] = field_start;
    ends[f] = end_pos;
    quoted[f] = was_quoted ? 1 : 0;
    f++;
    col++;
    was_quoted = false;
    return true;
  };

  while (i < len) {
    char ch = data[i];
    if (in_quotes) {
      if (ch == '"') {
        if (i + 1 < len && data[i + 1] == '"') i += 2;
        else { in_quotes = false; i++; }
      } else i++;
      continue;
    }
    if (ch == '"') {
      in_quotes = true;
      was_quoted = true;
      i++;
      continue;
    }
    if (ch == delim) {
      if (!end_field(i)) return -1;
      field_start = i + 1;
      i++;
      continue;
    }
    if (ch == '\n' || ch == '\r') {
      int64_t end_pos = i;
      bool empty_row = (col == 0 && field_start == end_pos && !was_quoted);
      if (ch == '\r' && i + 1 < len && data[i + 1] == '\n') i++;
      i++;
      if (empty_row) { field_start = i; continue; }
      if (!end_field(end_pos)) return -1;
      if (col != ncols) return -1;
      row++;
      col = 0;
      field_start = i;
      continue;
    }
    i++;
  }
  // trailing unterminated row
  if (col > 0 || field_start < len) {
    if (!(col == 0 && field_start == len)) {
      if (!end_field(len)) return -1;
      if (col != ncols) return -1;
      row++;
    }
  }
  return row;
}

}  // extern "C"
