"""Server instance: owns segments, executes per-segment query work.

Reference parity: pinot-server ServerInstance (.../starter/ServerInstance.java
:69-177) + HelixInstanceDataManager / BaseTableDataManager — the process that
holds segment data and runs the single-stage executor over its local
segments when the broker scatters a query.

Re-design: segments stay the same ImmutableSegment objects (in one process
the "download from deep store" step is a reference share / mmap re-open);
execution reuses the SSE executor with its device pytree cache, so each
logical server keeps its own HBM-resident working set.

Fault surface: the broker hands each scatter call a Deadline (its remaining
budget, optionally capped by serverTimeoutMs) — the launch/collect loop
checks it between kernels, and on expiry abandons still-pending launches
(cooperative cancellation: JAX dispatch is async, so "cancel" means never
collecting — no device_get, no host sync) before raising QueryTimeoutError.
An attached cluster.faults.FaultPlan can fail/delay the call or hide
segments, driving the broker's failover paths deterministically.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

from pinot_tpu.cluster.admission import QueryKilledError, ResourceBudget
from pinot_tpu.query import executor
from pinot_tpu.query.ir import QueryContext
from pinot_tpu.query.result import ExecutionStats
from pinot_tpu.query.safety import Deadline, QueryTimeoutError, estimate_segment_bytes
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.utils import perf
from pinot_tpu.utils.metrics import METRICS, MetricsRegistry


def _staging_depth() -> int:
    """Scatter staging window: how many consecutive segments must be
    jointly resident while the scan pages through the HBM cache.  Routed
    through the autopilot KnobRegistry (PINOT_TPU_STAGING_DEPTH initial,
    default 2 = current segment + the one prefetching behind it)."""
    from pinot_tpu.cluster import autopilot

    return max(1, int(autopilot.knobs().get("staging_depth")))


def _segment_bytes(segment: ImmutableSegment) -> int:
    """Host-array bytes of one segment (codes/values/null masks/MV lengths)
    — the per-table residency the segmentBytes gauge tracks."""
    total = 0
    for c in segment.columns.values():
        for arr in (c.codes, c.values, c.nulls, c.mv_lengths):
            if arr is not None:
                total += arr.nbytes
    return total


class ServerInstance:
    def __init__(
        self, name: str, device=None, fault_plan=None, budget=None, data_dir=None,
        residency=None,
    ):
        self.name = name
        self.device = device
        # table -> {segment name -> segment}
        self.segments: Dict[str, Dict[str, ImmutableSegment]] = {}
        # cluster.faults.FaultPlan hook (None in production)
        self.fault_plan = fault_plan
        # HBM reservation ledger (cluster.admission.ResourceBudget): every
        # scatter call reserves its working-set estimate before launching so
        # concurrent queries can't jointly overcommit device memory.  None
        # disables tracking; the coordinator attaches one at registration.
        self.budget: Optional[ResourceBudget] = budget
        # tiered storage (segment/residency.py): when attached, HBM is a
        # byte-budgeted CACHE over the segments' host arrays — scatter
        # calls reserve only the pipeline window (not the full working
        # set), segment columns page through the residency budget with
        # cost-aware eviction, and the next segment's columns prefetch on
        # the staging thread while the current kernel runs.  None keeps
        # the legacy pin-everything path.  The coordinator attaches one
        # at registration (PINOT_TPU_HBM_CACHE_BYTES=0 disables).
        self.residency = residency
        # local segment cache dir for deep-store restores (tempdir fallback)
        self.data_dir = data_dir
        # process-death simulation: True between crash() and boot() — every
        # execute fails like a dead TCP peer until the coordinator restarts
        # and reconciles this server
        self.crashed = False
        # per-SERVER metric registry (ServerMetrics analog): the broker
        # federates these into one labeled cluster exposition
        # (utils.metrics.federate_prometheus) — the process-global METRICS
        # keeps its role as this process's aggregate view
        self.metrics = MetricsRegistry()

    # -- crash / restart (process-death simulation) -----------------------
    def crash(self) -> None:
        """Simulate process death: all in-memory/HBM segment state is lost
        (gauges zero out with it) and calls fail until boot()."""
        for table in list(self.segments):
            for seg_name in list(self.segments[table]):
                self.drop_segment(table, seg_name)
        self.segments = {}
        self.crashed = True
        METRICS.counter("server.crashes").inc()

    def boot(self) -> None:
        """Come back up EMPTY — recovery is the coordinator reconciling this
        server against ideal state (restart_server), not a local replay."""
        self.crashed = False

    def restore_segment(self, table: str, seg_name: str, deep_store) -> ImmutableSegment:
        """Re-materialize one committed segment from the deep store: download
        to the local cache dir, CRC-verify, load, pin (restart recovery and
        rebalance both land here)."""
        import tempfile

        if self.data_dir is None:
            self.data_dir = tempfile.mkdtemp(prefix=f"pinot-server-{self.name}-")
        local_dir = os.path.join(self.data_dir, table)
        segment = deep_store.fetch_segment(table, seg_name, local_dir)
        self.add_segment(table, segment)
        return segment

    # -- data manager ----------------------------------------------------
    def add_segment(self, table: str, segment: ImmutableSegment) -> None:
        self.segments.setdefault(table, {})[segment.name] = segment
        # device-residency gauge: segment host arrays mirror what the
        # executor's pytree cache pins in HBM for this table
        METRICS.gauge(f"server.segmentBytes.{table}").add(_segment_bytes(segment))
        self.metrics.gauge(f"server.segmentBytes.{table}").add(_segment_bytes(segment))

    def drop_segment(self, table: str, seg_name: str) -> None:
        seg = self.segments.get(table, {}).pop(seg_name, None)
        if seg is not None:
            if self.residency is not None:
                # uncharge the cache budget AND drop the device entry;
                # the evict callback clears raw + #packed flavors together
                self.residency.evict(seg.device_group(self.device))
            # idempotent with the residency evict; also clears legacy pins
            seg.evict_device(self.device)
            METRICS.gauge(f"server.segmentBytes.{table}").add(-_segment_bytes(seg))
            self.metrics.gauge(f"server.segmentBytes.{table}").add(-_segment_bytes(seg))

    def get_segment(self, table: str, seg_name: str) -> Optional[ImmutableSegment]:
        return self.segments.get(table, {}).get(seg_name)

    def segment_names(self, table: str) -> List[str]:
        return list(self.segments.get(table, {}))

    # -- query execution (InstanceRequestHandler analog) ------------------
    def execute(
        self,
        ctx: QueryContext,
        seg_names: List[str],
        table_schema=None,
        deadline: Optional[Deadline] = None,
        cancel=None,
        source: str = "broker",
    ):
        """Run one query over the named LOCAL segments; returns
        (segment results, stats) — the DataTable the reference ships back.

        `cancel`: optional zero-arg probe (the broker watchdog's closure)
        returning a kill reason or None — checked between kernels alongside
        the deadline, so a killed query abandons its pending launches the
        same cooperative way a timed-out one does.  When `self.budget` is
        set, the working-set estimate for the named segments is reserved
        before any launch and released on exit (success, timeout, or kill) —
        a ReservationError here means this server is at capacity and the
        broker should fail the segments over to another replica.

        Tracing (ctx option `trace`): builds a per-server span subtree —
        dispatch (host-side plan+ship+async-launch per segment), device_wait
        (ONE block_until_ready over every pending output: the device-compute
        share the async dispatch hides), then per-segment collect spans —
        annotated with segments/docs/backend and any fault-plan events, and
        ships it back via stats.trace for the broker to graft."""
        from pinot_tpu.query.planner import _needed_columns
        from pinot_tpu.utils.metrics import Trace

        if self.crashed:
            from pinot_tpu.cluster.faults import ServerFaultError

            # a dead process looks like a transport error to the broker —
            # exactly the signal that drives its failover/breaker paths
            raise ServerFaultError(f"server {self.name} is down (crashed)")
        trace = Trace(bool(ctx.options.get("trace", False)), root=f"server:{self.name}")
        ticket = None
        if self.budget is not None:
            # working-set estimate for the batch, reserved all-or-nothing
            # BEFORE any kernel launches (host-side arithmetic only — no
            # device values touched, so the warm path stays sync-free)
            est = []
            for name in seg_names:
                seg = self.get_segment(ctx.table, name)
                if seg is not None:
                    est.append(estimate_segment_bytes(ctx, seg, _needed_columns(ctx, seg)))
            if self.residency is not None:
                # tiered storage: HBM is a cache, so a scatter only needs
                # its PIPELINE WINDOW resident at once (current segment +
                # the one prefetching behind it) — the residency manager
                # pages the rest through the budget as the scan advances.
                # Working sets that exceed free-but-not-total budget park
                # as a staged fetch instead of 503ing; a window that
                # exceeds the whole budget cannot fit even transiently
                # and still raises ReservationError.  The window width is
                # the autopilot staging_depth knob (read per decision).
                win = _staging_depth()
                need = max(
                    (sum(est[i : i + win]) for i in range(len(est))), default=0
                )
                ticket = self.budget.reserve_or_wait(
                    need, what=f"scatter to server {self.name}", deadline=deadline
                )
            else:
                ticket = self.budget.reserve(
                    sum(est), what=f"scatter to server {self.name}"
                )
        try:
            plan = self.fault_plan
            if plan is not None:
                fault_n0 = len(plan.log)
                # may sleep, flap liveness, or raise; `source` lets one-way
                # partition rules drop only this caller's direction
                plan.on_execute(self.name, source=source)
                if trace.enabled and len(plan.log) > fault_n0:
                    trace.annotate(faults=[k for (_, _, k, _) in plan.log[fault_n0:]])
            stats = ExecutionStats()
            results = []
            pending = []
            with trace.span("dispatch") as dsp:
                # host-side pre-filter FIRST: range/bloom metadata prunes
                # cold segments before any staging, so a pruned segment
                # never enters the host->device copy stream
                scan = []
                for name in seg_names:
                    seg = self.get_segment(ctx.table, name)
                    if seg is not None and plan is not None and plan.segment_dropped(self.name, ctx.table, name):
                        seg = None
                    if seg is None:
                        raise KeyError(f"server {self.name} does not serve {ctx.table}/{name}")
                    stats.num_segments_queried += 1
                    stats.total_docs += seg.num_docs
                    if table_schema is not None:
                        seg.ensure_columns(table_schema, _needed_columns(ctx, seg))
                    if executor.prune_segment(ctx, seg):
                        stats.num_segments_pruned += 1
                        continue
                    scan.append(seg)
                for k, seg in enumerate(scan):
                    self._check_budget(deadline, cancelled=len(pending), cancel=cancel)
                    if self.residency is not None and k + 1 < len(scan):
                        # double-buffer: stage segment k+1's columns on the
                        # residency staging thread while k dispatches/runs
                        nxt = scan[k + 1]
                        self.residency.submit(
                            nxt.to_device,
                            device=self.device,
                            columns=_needed_columns(ctx, nxt),
                            packed_codes=True,
                            residency=self.residency,
                            prefetch=True,
                        )
                    # pipelined: dispatch all kernels async, then drain (executor.py)
                    with trace.span(f"launch:{seg.name}") as lsp:
                        st = executor.launch_segment(
                            ctx, seg, device=self.device, residency=self.residency
                        )
                        pending.append(st)
                    if lsp is not None and st[0] == "pending":
                        # per-operator cost model for EXPLAIN ANALYZE / traces
                        lsp.annotate(
                            kernelBytes=st[5].kernel_bytes,
                            kernelFlops=st[5].kernel_flops,
                            costSource=st[5].kernel_cost_source,
                        )
                if dsp is not None:
                    dsp.annotate(launches=len(pending))
            if trace.enabled:
                # device/host time split: ONE fence over every pending output
                # (trace-only — the untraced path lets collect's device_get be
                # the fence so cancellation stays responsive between collects)
                import jax
                import time as _time

                pend_bytes = sum(
                    s[5].kernel_bytes for s in pending if s[0] == "pending"
                )
                tw = _time.perf_counter()
                with trace.span("device_wait", launches=len(pending)) as wsp:
                    jax.block_until_ready(executor.pending_outputs(pending))
                wait_s = _time.perf_counter() - tw
                stats.device_ms = wait_s * 1000.0
                if wsp is not None:
                    roof = perf.roofline_pct(pend_bytes, wait_s)
                    wsp.annotate(
                        kernelBytes=pend_bytes,
                        **({"rooflinePct": round(roof, 2)} if roof is not None else {}),
                    )
            for i, st in enumerate(pending):
                self._check_budget(deadline, cancelled=len(pending) - i, cancel=cancel)
                with trace.span("collect") as csp:
                    res, seg_stats = executor.collect_segment(st)
                if csp is not None:
                    csp.annotate(docs=seg_stats.num_docs_scanned)
                stats.num_segments_processed += 1
                stats.num_docs_scanned += seg_stats.num_docs_scanned
                stats.add_index_uses(seg_stats.filter_index_uses)
                stats.add_kernel_cost(seg_stats)
                results.append(res)
            # server-local series the broker federates into the cluster view
            self.metrics.counter("server.queries").inc()
            self.metrics.counter("server.docsScanned").inc(stats.num_docs_scanned)
            self.metrics.counter("server.kernelBytes").inc(int(stats.kernel_bytes))
            if stats.compile_ms > 0:
                self.metrics.timer("server.compileMs").update(stats.compile_ms)
            if trace.enabled:
                from pinot_tpu import ops

                trace.annotate(
                    server=self.name,
                    segments=len(seg_names),
                    segmentsPruned=stats.num_segments_pruned,
                    docsScanned=stats.num_docs_scanned,
                    backend=ops.scan_backend(),
                )
                stats.trace = trace.finish()
            return results, stats
        finally:
            if ticket is not None:
                self.budget.release(ticket)

    def execute_batch(
        self,
        ctxs: List[QueryContext],
        seg_names: List[str],
        table_schema=None,
        deadlines: Optional[List[Optional[Deadline]]] = None,
        cancels: Optional[List] = None,
        batch_id: Optional[str] = None,
        trace_enabled: bool = False,
        source: str = "broker",
    ):
        """Run N same-shape queries over the named LOCAL segments as ONE
        vmapped launch per segment (executor.launch_segment_batch); returns
        ``(results, stats, errors, batch_trace)`` with one slot per member.

        Per-member isolation: a member whose deadline expires or whose kill
        probe fires gets its error recorded in ``errors[i]`` and detaches —
        its remaining lanes are computed but discarded, and its siblings'
        results stay bit-exact.  Only BATCH-level faults raise out of this
        call (crashed server, fault-plan failure, missing segment,
        reservation exhaustion): the broker reacts by falling back to
        per-member execution through the normal failover machinery.

        Stats attribution: each segment's scanned docs and kernel
        bytes/flops divide across the members that actually scanned it
        (pruned members are excluded from the division), so summing member
        stats reproduces one unbatched run — never N duplicated copies.

        A per-member prune divergence within a uniform-segment batch is
        handled lane-wise: an all-pruned segment is skipped entirely, a
        partially-pruned one still launches with every live member's lane
        but credits pruned members with num_segments_pruned instead of
        docs."""
        from pinot_tpu.query.planner import _needed_columns
        from pinot_tpu.utils.metrics import Trace

        if self.crashed:
            from pinot_tpu.cluster.faults import ServerFaultError

            raise ServerFaultError(f"server {self.name} is down (crashed)")
        n = len(ctxs)
        deadlines = list(deadlines) if deadlines else [None] * n
        cancels = list(cancels) if cancels else [None] * n
        trace = Trace(trace_enabled, root=f"server:{self.name}")
        ticket = None
        if self.budget is not None:
            # members share one plan shape, so the working set is the
            # SHARED column pytree — reserved once, not once per member
            est = []
            for name in seg_names:
                seg = self.get_segment(ctxs[0].table, name)
                if seg is not None:
                    est.append(
                        estimate_segment_bytes(
                            ctxs[0], seg, _needed_columns(ctxs[0], seg)
                        )
                    )
            if self.residency is not None:
                # pipeline-window reservation (see execute): the cache
                # pages segments through the budget, so only the window
                # must be jointly resident
                win = _staging_depth()
                need = max(
                    (sum(est[i : i + win]) for i in range(len(est))), default=0
                )
                ticket = self.budget.reserve_or_wait(
                    need, what=f"batched scatter to server {self.name}"
                )
            else:
                ticket = self.budget.reserve(
                    sum(est), what=f"batched scatter to server {self.name}"
                )
        try:
            plan = self.fault_plan
            if plan is not None:
                fault_n0 = len(plan.log)
                plan.on_execute(self.name, source=source)  # may sleep, flap liveness, or raise
                if trace.enabled and len(plan.log) > fault_n0:
                    trace.annotate(faults=[k for (_, _, k, _) in plan.log[fault_n0:]])
            stats = [ExecutionStats() for _ in range(n)]
            results: List[list] = [[] for _ in range(n)]
            errors: List[Optional[Exception]] = [None] * n
            pending = []  # (launch state, member indices it carries)
            with trace.span("dispatch", batchId=batch_id, batchSize=n) as dsp:
                for name in seg_names:
                    self._probe_members(deadlines, cancels, errors)
                    live = [i for i in range(n) if errors[i] is None]
                    if not live:
                        break
                    seg = self.get_segment(ctxs[0].table, name)
                    if seg is not None and plan is not None and plan.segment_dropped(
                        self.name, ctxs[0].table, name
                    ):
                        seg = None
                    if seg is None:
                        raise KeyError(
                            f"server {self.name} does not serve {ctxs[0].table}/{name}"
                        )
                    for i in live:
                        stats[i].num_segments_queried += 1
                        stats[i].total_docs += seg.num_docs
                    if table_schema is not None:
                        seg.ensure_columns(table_schema, _needed_columns(ctxs[0], seg))
                    scan = []
                    for i in live:
                        if executor.prune_segment(ctxs[i], seg):
                            stats[i].num_segments_pruned += 1
                        else:
                            scan.append(i)
                    if not scan:
                        continue
                    with trace.span(f"launch:{seg.name}", members=len(scan)):
                        if len(scan) == 1:
                            st = executor.launch_segment(
                                ctxs[scan[0]], seg, device=self.device,
                                residency=self.residency,
                            )
                            pending.append((st, scan))
                        else:
                            try:
                                st = executor.launch_segment_batch(
                                    [ctxs[i] for i in scan], seg, device=self.device,
                                    residency=self.residency,
                                )
                                pending.append((st, scan))
                            except executor.BatchShapeError:
                                # vetted batches shouldn't land here; stay
                                # correct with per-member launches if one does
                                for i in scan:
                                    pending.append(
                                        (
                                            executor.launch_segment(
                                                ctxs[i], seg, device=self.device,
                                                residency=self.residency,
                                            ),
                                            [i],
                                        )
                                    )
                if dsp is not None:
                    dsp.annotate(launches=len(pending))
            if trace.enabled:
                import jax
                import time as _time

                tw = _time.perf_counter()
                with trace.span("device_wait", launches=len(pending)):
                    jax.block_until_ready(
                        executor.pending_outputs([p[0] for p in pending])
                    )
                wait_ms = (_time.perf_counter() - tw) * 1000.0
                live = [i for i in range(n) if errors[i] is None]
                for i in live:
                    stats[i].device_ms = wait_ms / max(1, len(live))
            for st, members in pending:
                self._probe_members(deadlines, cancels, errors, only=members)
                alive = [i for i in members if errors[i] is None]
                if not alive:
                    continue  # every rider died — abandon uncollected
                with trace.span("collect", members=len(alive)) as csp:
                    if st[0] == "pending_batch":
                        collected = executor.collect_segment_batch(st)
                    else:
                        collected = [executor.collect_segment(st)]
                docs = 0
                for (res, seg_st), i in zip(collected, members):
                    if errors[i] is not None:
                        continue  # killed member's lane computed but discarded
                    stats[i].num_segments_processed += 1
                    stats[i].num_docs_scanned += seg_st.num_docs_scanned
                    stats[i].add_index_uses(seg_st.filter_index_uses)
                    stats[i].add_kernel_cost(seg_st)
                    results[i].append(res)
                    docs += seg_st.num_docs_scanned
                if csp is not None:
                    csp.annotate(docs=docs)
            served = sum(1 for e in errors if e is None)
            self.metrics.counter("server.queries").inc(served)
            self.metrics.counter("server.batches").inc()
            self.metrics.histogram("server.batchSize").update(n)
            METRICS.counter("server.batches").inc()
            METRICS.histogram("server.batchSize").update(n)
            docs_total = sum(s.num_docs_scanned for s in stats)
            self.metrics.counter("server.docsScanned").inc(docs_total)
            self.metrics.counter("server.kernelBytes").inc(
                int(sum(s.kernel_bytes for s in stats))
            )
            killed = sum(
                1 for e in errors if isinstance(e, QueryKilledError)
            )
            if killed:
                METRICS.counter("server.queriesKilled").inc(killed)
            batch_trace = None
            if trace.enabled:
                from pinot_tpu import ops

                trace.annotate(
                    server=self.name,
                    batchId=batch_id,
                    batchSize=n,
                    segments=len(seg_names),
                    docsScanned=docs_total,
                    backend=ops.scan_backend(),
                )
                batch_trace = trace.finish()
            return results, stats, errors, batch_trace
        finally:
            if ticket is not None:
                self.budget.release(ticket)

    def _probe_members(
        self,
        deadlines: List[Optional[Deadline]],
        cancels: List,
        errors: List[Optional[Exception]],
        only: Optional[List[int]] = None,
    ) -> None:
        """Per-member deadline + kill probes for a batched call.  A firing
        probe records the member's error (detaching it from the batch)
        instead of raising — siblings keep their lanes and their results."""
        idx = only if only is not None else range(len(errors))
        for i in idx:
            if errors[i] is not None:
                continue
            cancel = cancels[i]
            if cancel is not None:
                reason = cancel()
                if reason:
                    errors[i] = QueryKilledError(
                        f"server {self.name}: query killed ({reason}); "
                        "batch member detached",
                        reason=reason,
                    )
                    continue
            deadline = deadlines[i]
            if deadline is not None and deadline.expired():
                errors[i] = QueryTimeoutError(
                    f"server {self.name} ran out of query budget "
                    f"(timeoutMs={deadline.timeout_ms:g}); batch member detached"
                )

    def _check_budget(
        self, deadline: Optional[Deadline], cancelled: int, cancel=None
    ) -> None:
        """Between-kernel deadline + kill probe.  On expiry or kill the
        still-pending launches are abandoned uncollected (their references
        die with this frame — the async dispatches finish on device but
        never sync back)."""
        if cancel is not None:
            reason = cancel()
            if reason:
                if cancelled:
                    METRICS.counter("server.launchesCancelled").inc(cancelled)
                METRICS.counter("server.queriesKilled").inc()
                raise QueryKilledError(
                    f"server {self.name}: query killed ({reason}); "
                    f"{cancelled} pending launch(es) abandoned",
                    reason=reason,
                )
        if deadline is not None and deadline.expired():
            if cancelled:
                METRICS.counter("server.launchesCancelled").inc(cancelled)
            raise QueryTimeoutError(
                f"server {self.name} ran out of query budget "
                f"(timeoutMs={deadline.timeout_ms:g}); "
                f"{cancelled} pending launch(es) abandoned"
            )
