"""Server instance: owns segments, executes per-segment query work.

Reference parity: pinot-server ServerInstance (.../starter/ServerInstance.java
:69-177) + HelixInstanceDataManager / BaseTableDataManager — the process that
holds segment data and runs the single-stage executor over its local
segments when the broker scatters a query.

Re-design: segments stay the same ImmutableSegment objects (in one process
the "download from deep store" step is a reference share / mmap re-open);
execution reuses the SSE executor with its device pytree cache, so each
logical server keeps its own HBM-resident working set.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from pinot_tpu.query import executor, reduce as reduce_mod
from pinot_tpu.query.ir import QueryContext
from pinot_tpu.query.result import ExecutionStats
from pinot_tpu.segment.segment import ImmutableSegment


class ServerInstance:
    def __init__(self, name: str, device=None):
        self.name = name
        self.device = device
        # table -> {segment name -> segment}
        self.segments: Dict[str, Dict[str, ImmutableSegment]] = {}

    # -- data manager ----------------------------------------------------
    def add_segment(self, table: str, segment: ImmutableSegment) -> None:
        self.segments.setdefault(table, {})[segment.name] = segment

    def drop_segment(self, table: str, seg_name: str) -> None:
        self.segments.get(table, {}).pop(seg_name, None)

    def get_segment(self, table: str, seg_name: str) -> Optional[ImmutableSegment]:
        return self.segments.get(table, {}).get(seg_name)

    def segment_names(self, table: str) -> List[str]:
        return list(self.segments.get(table, {}))

    # -- query execution (InstanceRequestHandler analog) ------------------
    def execute(self, ctx: QueryContext, seg_names: List[str], table_schema=None):
        """Run one query over the named LOCAL segments; returns
        (segment results, stats) — the DataTable the reference ships back."""
        from pinot_tpu.query.planner import _needed_columns

        stats = ExecutionStats()
        results = []
        pending = []
        for name in seg_names:
            seg = self.get_segment(ctx.table, name)
            if seg is None:
                raise KeyError(f"server {self.name} does not serve {ctx.table}/{name}")
            stats.num_segments_queried += 1
            stats.total_docs += seg.num_docs
            if table_schema is not None:
                seg.ensure_columns(table_schema, _needed_columns(ctx, seg))
            if executor.prune_segment(ctx, seg):
                stats.num_segments_pruned += 1
                continue
            # pipelined: dispatch all kernels async, then drain (executor.py)
            pending.append(executor.launch_segment(ctx, seg, device=self.device))
        for st in pending:
            res, seg_stats = executor.collect_segment(st)
            stats.num_segments_processed += 1
            stats.num_docs_scanned += seg_stats.num_docs_scanned
            stats.add_index_uses(seg_stats.filter_index_uses)
            results.append(res)
        return results, stats
