"""Server instance: owns segments, executes per-segment query work.

Reference parity: pinot-server ServerInstance (.../starter/ServerInstance.java
:69-177) + HelixInstanceDataManager / BaseTableDataManager — the process that
holds segment data and runs the single-stage executor over its local
segments when the broker scatters a query.

Re-design: segments stay the same ImmutableSegment objects (in one process
the "download from deep store" step is a reference share / mmap re-open);
execution reuses the SSE executor with its device pytree cache, so each
logical server keeps its own HBM-resident working set.

Fault surface: the broker hands each scatter call a Deadline (its remaining
budget, optionally capped by serverTimeoutMs) — the launch/collect loop
checks it between kernels, and on expiry abandons still-pending launches
(cooperative cancellation: JAX dispatch is async, so "cancel" means never
collecting — no device_get, no host sync) before raising QueryTimeoutError.
An attached cluster.faults.FaultPlan can fail/delay the call or hide
segments, driving the broker's failover paths deterministically.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from pinot_tpu.query import executor
from pinot_tpu.query.ir import QueryContext
from pinot_tpu.query.result import ExecutionStats
from pinot_tpu.query.safety import Deadline, QueryTimeoutError
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.utils.metrics import METRICS


class ServerInstance:
    def __init__(self, name: str, device=None, fault_plan=None):
        self.name = name
        self.device = device
        # table -> {segment name -> segment}
        self.segments: Dict[str, Dict[str, ImmutableSegment]] = {}
        # cluster.faults.FaultPlan hook (None in production)
        self.fault_plan = fault_plan

    # -- data manager ----------------------------------------------------
    def add_segment(self, table: str, segment: ImmutableSegment) -> None:
        self.segments.setdefault(table, {})[segment.name] = segment

    def drop_segment(self, table: str, seg_name: str) -> None:
        self.segments.get(table, {}).pop(seg_name, None)

    def get_segment(self, table: str, seg_name: str) -> Optional[ImmutableSegment]:
        return self.segments.get(table, {}).get(seg_name)

    def segment_names(self, table: str) -> List[str]:
        return list(self.segments.get(table, {}))

    # -- query execution (InstanceRequestHandler analog) ------------------
    def execute(
        self,
        ctx: QueryContext,
        seg_names: List[str],
        table_schema=None,
        deadline: Optional[Deadline] = None,
    ):
        """Run one query over the named LOCAL segments; returns
        (segment results, stats) — the DataTable the reference ships back."""
        from pinot_tpu.query.planner import _needed_columns

        plan = self.fault_plan
        if plan is not None:
            plan.on_execute(self.name)  # may sleep, flap liveness, or raise
        stats = ExecutionStats()
        results = []
        pending = []
        for name in seg_names:
            self._check_budget(deadline, cancelled=len(pending))
            seg = self.get_segment(ctx.table, name)
            if seg is not None and plan is not None and plan.segment_dropped(self.name, ctx.table, name):
                seg = None
            if seg is None:
                raise KeyError(f"server {self.name} does not serve {ctx.table}/{name}")
            stats.num_segments_queried += 1
            stats.total_docs += seg.num_docs
            if table_schema is not None:
                seg.ensure_columns(table_schema, _needed_columns(ctx, seg))
            if executor.prune_segment(ctx, seg):
                stats.num_segments_pruned += 1
                continue
            # pipelined: dispatch all kernels async, then drain (executor.py)
            pending.append(executor.launch_segment(ctx, seg, device=self.device))
        for i, st in enumerate(pending):
            self._check_budget(deadline, cancelled=len(pending) - i)
            res, seg_stats = executor.collect_segment(st)
            stats.num_segments_processed += 1
            stats.num_docs_scanned += seg_stats.num_docs_scanned
            stats.add_index_uses(seg_stats.filter_index_uses)
            results.append(res)
        return results, stats

    def _check_budget(self, deadline: Optional[Deadline], cancelled: int) -> None:
        """Between-kernel deadline check.  On expiry the still-pending
        launches are abandoned uncollected (their references die with this
        frame — the async dispatches finish on device but never sync back)."""
        if deadline is not None and deadline.expired():
            if cancelled:
                METRICS.counter("server.launchesCancelled").inc(cancelled)
            raise QueryTimeoutError(
                f"server {self.name} ran out of query budget "
                f"(timeoutMs={deadline.timeout_ms:g}); "
                f"{cancelled} pending launch(es) abandoned"
            )
