"""Resource governance for the serving tier: admission control, memory
reservations, runaway-query kills, graceful degradation.

Reference parity (SURVEY.md 5.2): Pinot's resource-accounted query scheduler
(ResourceManager / PriorityScheduler admission), the OOM-protecting query
killer (QueryMonitor + PerQueryCPUMemAccountantFactory picks the most
expensive query under heap pressure and interrupts it), and broker-side
request throttling (QueryQuotaManager, but per-cost rather than per-count).

Re-design for the TPU serving tier:

  * COST is estimated up front from broker-side segment metadata (rows the
    plan will scan, HBM bytes the kernels will touch, a group-by
    cardinality bound) instead of sampled mid-flight — static shapes make
    the working set predictable before launch.
  * ADMISSION is a token bucket denominated in cost units with a BOUNDED
    wait queue: a query over budget either waits (bounded, deadline-capped)
    or is shed immediately with a structured 429 — never queued unboundedly.
  * RESERVATIONS: every scatter call reserves its working-set estimate
    against the target server's HBM budget BEFORE launching and releases on
    completion/cancel, so concurrent queries cannot collectively overcommit
    device memory; caches (broker results, compiled plans) charge the SAME
    host-side ledger the admission controller tracks.
  * KILLS ride the existing cooperative between-kernel cancellation (r7):
    the watchdog marks a query dead (deadline/runaway/pressure), the server
    observes the mark between segment kernels and abandons still-pending
    launches uncollected — no device sync on the warm path (DrJAX
    static-control framing: admission decisions are host control flow).
  * DEGRADATION under sustained pressure is progressive and observable:
    result cache off, macro-batch pipeline depth shrunk, low-priority
    queries shed first — all published as gauges + span annotations.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from pinot_tpu.query.ir import QueryContext
from pinot_tpu.query.safety import AdmissionError, Deadline
from pinot_tpu.utils import threads
from pinot_tpu.utils.metrics import METRICS


class TooManyRequestsError(RuntimeError):
    """Admission shed: the serving tier is over its rate budget and this
    query was rejected up front (REST 429 TOO_MANY_REQUESTS_ERROR).
    Carries the minted query id so throttled clients can correlate."""

    def __init__(self, message: str, query_id: Optional[str] = None):
        super().__init__(message)
        self.query_id = query_id


class ReservationError(AdmissionError):
    """A working-set reservation could not be acquired — the HBM or host
    budget is committed to other in-flight work (REST 503
    SERVER_OUT_OF_CAPACITY; retryable, capacity returns as queries drain)."""

    def __init__(self, message: str, query_id: Optional[str] = None):
        super().__init__(message)
        self.query_id = query_id


class QueryKilledError(RuntimeError):
    """The watchdog killed this query mid-flight (deadline overrun, runaway
    runtime, or global memory pressure); pending kernel launches were
    abandoned uncollected (cooperative cancellation)."""

    def __init__(self, message: str, query_id: Optional[str] = None, reason: str = ""):
        super().__init__(message)
        self.query_id = query_id
        self.reason = reason or message


# ---------------------------------------------------------------------------
# cost estimation
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class QueryCost:
    """Up-front cost estimate for one query: what the admission bucket is
    charged (units) and what the reservations will pin (bytes)."""

    rows: int  # rows the scatter will scan (post broker-side metadata)
    hbm_bytes: int  # device bytes the segment kernels touch
    group_cardinality: int  # group-table bound (num_groups_limit)
    host_bytes: int  # host-side reduce/merge footprint charged to the host ledger

    # one unit ~ a small interactive query; wide scans/aggregations cost more
    ROWS_PER_UNIT = 5_000_000
    BYTES_PER_UNIT = 256 << 20
    GROUPS_PER_UNIT = 200_000

    @property
    def units(self) -> float:
        return (
            1.0
            + self.rows / self.ROWS_PER_UNIT
            + self.hbm_bytes / self.BYTES_PER_UNIT
            + self.group_cardinality / self.GROUPS_PER_UNIT
        )


def estimate_query_cost(ctx: QueryContext, segment_metas) -> QueryCost:
    """Broker-side cost estimate from segment metadata (coordinator
    TableMeta.segment_meta values): rows scanned is the doc total of the
    candidate segments, HBM bytes their host-array residency (the kernels
    ship a subset of it), and the group-by bound is the plan's
    numGroupsLimit — the same three axes the reference's accountant samples,
    computed before launch instead."""
    rows = 0
    hbm = 0
    for sm in segment_metas:
        if not isinstance(sm, dict):
            continue
        docs = int(sm.get("numDocs", 0) or 0)
        rows += docs
        b = sm.get("bytes")
        hbm += int(b) if b is not None else docs * 16  # ~2 narrow columns fallback
    groups = int(ctx.num_groups_limit) if ctx.group_by else 0
    n_aggs = max(1, len(ctx.aggregations))
    host = groups * 16 * n_aggs + (64 << 10)  # group tables + fixed reduce slack
    return QueryCost(rows=rows, hbm_bytes=hbm, group_cardinality=groups, host_bytes=host)


# ---------------------------------------------------------------------------
# token-bucket admission with a bounded wait queue
# ---------------------------------------------------------------------------
class AdmissionController:
    """Cost-denominated token bucket (refill `rate` units/s, burst capacity
    `burst`) with a BOUNDED wait queue: when tokens are short a normal-
    priority query may wait (at most `max_queue` waiters, each capped by
    min(max_wait_ms, its remaining deadline)); a low-priority query, or any
    query once the queue is full, is shed immediately with a structured
    TooManyRequestsError.  rate <= 0 disables admission entirely (the
    default — governance is opt-in per deployment)."""

    def __init__(
        self,
        rate_units_per_s: float = 0.0,
        burst_units: Optional[float] = None,
        max_queue: int = 8,
        max_wait_ms: float = 500.0,
        knob: Optional[str] = None,
    ):
        self.rate = float(rate_units_per_s)
        # when `knob` names an autopilot KnobRegistry entry (the governor
        # passes "admission_rate"), the refill rate is read from the
        # registry per decision — a controller write takes effect on the
        # next refill without rebuilding; burst/queue stay static ceilings
        self.knob = knob
        self.burst = float(burst_units) if burst_units is not None else max(1.0, self.rate)
        self.max_queue = int(max_queue)
        self.max_wait_ms = float(max_wait_ms)
        self.clock = time.monotonic  # injectable for deterministic tests
        # Condition wraps the bucket lock: waiters re-check on wake, and the
        # refill/charge sequence is a read-modify-write (same race class as
        # the broker token bucket, ADVICE r5)
        self._lock = threads.Condition()
        self._tokens = self.burst
        self._last_refill: Optional[float] = None
        self._waiting = 0

    def _rate_now(self) -> float:
        """Effective refill rate for THIS decision: the KnobRegistry value
        when knob-managed (clamped to the static env ceiling by the
        registry), else the construction-time rate."""
        if self.knob is None:
            return self.rate
        from pinot_tpu.cluster import autopilot

        return float(autopilot.knobs().get(self.knob))

    def _refill_locked(self, now: float) -> None:
        if self._last_refill is None:
            self._last_refill = now
        self._tokens = min(
            self.burst, self._tokens + self._rate_now() * (now - self._last_refill)
        )
        self._last_refill = now

    def tokens(self) -> float:
        with self._lock:
            self._refill_locked(self.clock())
            return self._tokens

    def deficit(self) -> float:
        """Bucket exhaustion in [0, 1]: 0 = full burst available, 1 = dry.
        One input to the degradation controller's pressure signal."""
        if self._rate_now() <= 0:
            return 0.0
        with self._lock:
            self._refill_locked(self.clock())
            return max(0.0, 1.0 - self._tokens / self.burst)

    def _shed(self, query_id: Optional[str], detail: str) -> None:
        METRICS.counter("admission.shed").inc()
        raise TooManyRequestsError(
            f"query {query_id}: admission shed ({detail}); back off and retry",
            query_id=query_id,
        )

    def admit(
        self,
        query_id: Optional[str],
        units: float = 1.0,
        priority: int = 0,
        deadline: Optional[Deadline] = None,
    ) -> None:
        """Charge `units` or raise TooManyRequestsError.  Tokens are repaid
        by time, not by completion — the bucket bounds offered RATE; the
        reservation ledgers bound concurrent FOOTPRINT."""
        if self._rate_now() <= 0:
            return
        # a single query costlier than the whole burst must still be servable
        units = min(float(units), self.burst)
        with self._lock:
            self._refill_locked(self.clock())
            if self._tokens >= units:
                self._tokens -= units
                METRICS.counter("admission.admitted").inc()
                return
            if priority < 0:
                self._shed(query_id, "low-priority query under load")
            if self.max_queue <= 0 or self._waiting >= self.max_queue:
                self._shed(query_id, f"wait queue full ({self.max_queue} slots)")
            budget_ms = self.max_wait_ms
            if deadline is not None:
                rem = deadline.remaining_ms()
                if rem is not None:
                    budget_ms = min(budget_ms, rem)
            start = self.clock()
            self._waiting += 1
            METRICS.gauge("admission.queuedQueries").set(float(self._waiting))
            try:
                while True:
                    now = self.clock()
                    self._refill_locked(now)
                    if self._tokens >= units:
                        self._tokens -= units
                        METRICS.counter("admission.admitted").inc()
                        METRICS.counter("admission.admittedAfterWait").inc()
                        return
                    waited_ms = (now - start) * 1000
                    if waited_ms >= budget_ms:
                        self._shed(query_id, f"queued {waited_ms:.0f} ms without a token")
                    need_s = (units - self._tokens) / max(self._rate_now(), 1e-9)
                    self._lock.wait(timeout=min(need_s, (budget_ms - waited_ms) / 1000))
            finally:
                self._waiting -= 1
                METRICS.gauge("admission.queuedQueries").set(float(self._waiting))

    def try_charge(self, units: float = 1.0) -> bool:
        """Non-blocking charge for OPTIONAL work (hedged backups): take
        `units` only if available right now, never queue, never shed.  Under
        token scarcity this returns False while admit() can still queue —
        exactly the ordering that throttles hedges before primaries."""
        if self._rate_now() <= 0:
            return True
        units = min(float(units), self.burst)
        with self._lock:
            self._refill_locked(self.clock())
            if self._tokens >= units:
                self._tokens -= units
                return True
            return False

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            self._refill_locked(self.clock())
            return {
                "rate": self._rate_now(),
                "staticRate": self.rate,
                "burst": self.burst,
                "tokens": round(self._tokens, 3),
                "waiting": self._waiting,
                "maxQueue": self.max_queue,
            }


# ---------------------------------------------------------------------------
# byte reservations (HBM per server, host memory process-wide)
# ---------------------------------------------------------------------------
class ResourceBudget:
    """Thread-safe byte ledger with two clients on ONE budget:

      * queries `reserve()` their working-set estimate before launch and
        `release()` on completion/cancel (raises ReservationError when the
        budget is committed — REST 503 SERVER_OUT_OF_CAPACITY);
      * caches `try_charge()` / `uncharge()` bytes they retain (never raise
        — a full budget just means the cache evicts instead of growing).

    Because both ride the same ledger, cached bytes and in-flight working
    sets cannot jointly overcommit (ISSUE r11 satellite: the caches used to
    bound themselves independently).  `gauge` names the published METRICS
    gauge; `peak` is the high-water mark the overload tests assert against
    the configured budget."""

    def __init__(self, budget_bytes: int, gauge: Optional[str] = None):
        self.budget_bytes = int(budget_bytes)
        self.gauge = gauge
        self.clock = time.monotonic  # injectable for deterministic tests
        # Condition, not a bare Lock: reserve_or_wait() parks staged
        # fetches on it until release()/uncharge() frees bytes.
        self._lock = threads.Condition()
        self._by_ticket: Dict[int, int] = {}
        self._ticket_seq = itertools.count(1)
        self._in_use = 0
        self._peak = 0
        self._waiters = 0

    def _publish_locked(self) -> None:
        if self.gauge is not None:
            METRICS.gauge(self.gauge).set(float(self._in_use))

    @property
    def in_use(self) -> int:
        with self._lock:
            return self._in_use

    @property
    def peak(self) -> int:
        """High-water mark of concurrent bytes — never exceeds budget_bytes
        by construction (the overload acceptance assertion)."""
        with self._lock:
            return self._peak

    def available(self) -> int:
        with self._lock:
            return max(0, self.budget_bytes - self._in_use)

    def occupancy(self) -> float:
        with self._lock:
            return self._in_use / self.budget_bytes if self.budget_bytes > 0 else 0.0

    def reserve(self, nbytes: int, what: str = "query", query_id: Optional[str] = None) -> int:
        """Admit `nbytes` or raise ReservationError; returns a ticket for
        release().  All-or-nothing: a partial reservation would deadlock
        against other partial holders."""
        n = max(0, int(nbytes))
        with self._lock:
            if self._in_use + n > self.budget_bytes:
                METRICS.counter("admission.reservationRejected").inc()
                raise ReservationError(
                    f"{what} needs ~{n / 1e6:.1f} MB but only "
                    f"{(self.budget_bytes - self._in_use) / 1e6:.1f} MB of "
                    f"{self.budget_bytes / 1e6:.1f} MB remain reserved-free",
                    query_id=query_id,
                )
            return self._reserve_locked(n)

    def reserve_or_wait(
        self,
        nbytes: int,
        what: str = "query",
        query_id: Optional[str] = None,
        deadline: Optional[Deadline] = None,
        max_wait_ms: Optional[float] = None,
        queue_limit: int = 8,
    ) -> int:
        """Tiered-storage admission (ISSUE r17): a working set that exceeds
        the *currently free* budget but fits the TOTAL budget is a staged
        fetch — park (bounded, deadline-capped) until running queries
        release bytes, instead of 503ing.  ReservationError still raises
        immediately when the working set cannot fit even transiently
        (nbytes > budget_bytes) or the staged-fetch queue is full, and on
        wait timeout — those remain SERVER_OUT_OF_CAPACITY."""
        n = max(0, int(nbytes))
        if max_wait_ms is None:
            max_wait_ms = float(os.environ.get("PINOT_TPU_STAGED_FETCH_MS", "250"))
        with self._lock:
            if n > self.budget_bytes:
                METRICS.counter("admission.reservationRejected").inc()
                raise ReservationError(
                    f"{what} needs ~{n / 1e6:.1f} MB but the whole budget is "
                    f"{self.budget_bytes / 1e6:.1f} MB — cannot fit even "
                    "transiently",
                    query_id=query_id,
                )
            if self._in_use + n <= self.budget_bytes:
                return self._reserve_locked(n)
            if self._waiters >= queue_limit:
                METRICS.counter("admission.stagedFetchRejected").inc()
                raise ReservationError(
                    f"{what} staged-fetch queue full ({queue_limit} waiting)",
                    query_id=query_id,
                )
            budget_ms = max_wait_ms
            if deadline is not None:
                budget_ms = min(budget_ms, deadline.remaining_ms())
            give_up = self.clock() + max(0.0, budget_ms) / 1000.0
            METRICS.counter("admission.stagedFetchQueued").inc()
            self._waiters += 1
            try:
                while self._in_use + n > self.budget_bytes:
                    left = give_up - self.clock()
                    if left <= 0 or not self._lock.wait(timeout=left):
                        METRICS.counter("admission.stagedFetchTimeouts").inc()
                        raise ReservationError(
                            f"{what} needs ~{n / 1e6:.1f} MB; still only "
                            f"{(self.budget_bytes - self._in_use) / 1e6:.1f} MB "
                            f"free after {budget_ms:.0f} ms staged wait",
                            query_id=query_id,
                        )
            finally:
                self._waiters -= 1
            METRICS.counter("admission.stagedFetchServed").inc()
            return self._reserve_locked(n)

    def _reserve_locked(self, n: int) -> int:
        # callers hold self._lock (the _locked suffix contract; the W010
        # interprocedural pass verifies every call site)
        ticket = next(self._ticket_seq)
        self._by_ticket[ticket] = n
        self._in_use += n  # pinot-lint: disable=W004
        self._peak = max(self._peak, self._in_use)
        self._publish_locked()
        return ticket

    def release(self, ticket: int) -> int:
        with self._lock:
            n = self._by_ticket.pop(ticket, 0)
            self._in_use -= n
            self._publish_locked()
            self._lock.notify_all()
            return n

    def try_charge(self, nbytes: int) -> bool:
        """Cache-side charge: False when it would overcommit (caller evicts
        or drops the entry instead of growing)."""
        n = max(0, int(nbytes))
        with self._lock:
            if self._in_use + n > self.budget_bytes:
                return False
            self._in_use += n
            self._peak = max(self._peak, self._in_use)
            self._publish_locked()
            return True

    def uncharge(self, nbytes: int) -> None:
        n = max(0, int(nbytes))
        with self._lock:
            self._in_use = max(0, self._in_use - n)
            self._publish_locked()
            self._lock.notify_all()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "budgetBytes": self.budget_bytes,
                "inUseBytes": self._in_use,
                "peakBytes": self._peak,
                "reservations": len(self._by_ticket),
            }


# ---------------------------------------------------------------------------
# runaway-query watchdog
# ---------------------------------------------------------------------------
@dataclass
class KillRecord:
    """What the watchdog knew at kill time — shipped to the slow log, the
    trace tree, and the bounded kill ring behind /debug/admission."""

    query_id: str
    reason: str
    reserved_bytes: int
    elapsed_ms: float
    priority: int

    def to_dict(self) -> Dict[str, Any]:
        return {
            "queryId": self.query_id,
            "reason": self.reason,
            "reservedBytes": self.reserved_bytes,
            "elapsedMs": round(self.elapsed_ms, 3),
            "priority": self.priority,
        }


class QueryWatchdog:
    """Marks in-flight queries dead; servers observe the mark between
    segment kernels (the r7 cooperative-cancellation check) and abandon
    still-pending launches uncollected.  Kill triggers:

      * runaway runtime — a registered query past its `runaway_ms` ceiling
        is marked on the next between-kernel probe (lazy, no patrol thread);
      * explicit `kill()` (operator / deadline escalation);
      * global pressure — `patrol(occupancy)` past `pressure_kill_at` picks
        a victim (lowest priority, then largest reservation), mirroring the
        reference QueryMonitor's kill-the-most-expensive heuristic.

    Everything here is host-side control flow: probes read a dict under a
    lock, never a device value (W013/W014 stay clean by construction)."""

    def __init__(self, runaway_ms: float = 0.0, pressure_kill_at: float = 0.0):
        self.runaway_ms = float(runaway_ms)  # 0 = no runtime ceiling
        self.pressure_kill_at = float(pressure_kill_at)  # 0 = pressure kills off
        self.clock = time.monotonic  # injectable for deterministic tests
        self._lock = threading.Lock()
        self._active: Dict[str, Dict[str, Any]] = {}
        self._killed: Dict[str, str] = {}
        self.kill_log: deque = deque(maxlen=64)  # bounded ring of KillRecords

    def register(
        self,
        query_id: str,
        reserved_bytes: int = 0,
        priority: int = 0,
        runaway_ms: Optional[float] = None,
    ) -> None:
        with self._lock:
            self._active[query_id] = {
                "started": self.clock(),
                "reserved": int(reserved_bytes),
                "priority": int(priority),
                "runaway_ms": self.runaway_ms if runaway_ms is None else float(runaway_ms),
            }
            METRICS.gauge("admission.activeQueries").set(float(len(self._active)))

    def deregister(self, query_id: str) -> None:
        with self._lock:
            self._active.pop(query_id, None)
            self._killed.pop(query_id, None)
            METRICS.gauge("admission.activeQueries").set(float(len(self._active)))

    def _kill_locked(self, query_id: str, reason: str) -> Optional[KillRecord]:
        reg = self._active.get(query_id)
        if reg is None or query_id in self._killed:
            return None
        self._killed[query_id] = reason
        rec = KillRecord(
            query_id=query_id,
            reason=reason,
            reserved_bytes=reg["reserved"],
            elapsed_ms=(self.clock() - reg["started"]) * 1000,
            priority=reg["priority"],
        )
        self.kill_log.append(rec)
        METRICS.counter("admission.queriesKilled").inc()
        return rec

    def kill(self, query_id: str, reason: str) -> bool:
        with self._lock:
            return self._kill_locked(query_id, reason) is not None

    def kill_reason(self, query_id: str) -> Optional[str]:
        """The between-kernel probe: a killed query's reason, marking lazy
        runaway overruns on the way (no patrol thread needed — the query
        polls its own death sentence between launches)."""
        now = self.clock()
        with self._lock:
            reason = self._killed.get(query_id)
            if reason is not None:
                return reason
            reg = self._active.get(query_id)
            if reg is None:
                return None
            ceiling = reg["runaway_ms"]
            if ceiling and ceiling > 0 and (now - reg["started"]) * 1000 > ceiling:
                rec = self._kill_locked(
                    query_id, f"runaway: exceeded maxRuntimeMs={ceiling:g}"
                )
                return rec.reason if rec is not None else self._killed.get(query_id)
            return None

    def cancel_probe(self, query_id: str) -> Callable[[], Optional[str]]:
        """Closure the broker threads through to ServerInstance.execute —
        checked between kernels, host-side only."""
        return lambda: self.kill_reason(query_id)

    def patrol(self, occupancy: float) -> Optional[KillRecord]:
        """Pressure-triggered victim selection: above the kill threshold,
        mark the lowest-priority / largest-reservation live query."""
        if self.pressure_kill_at <= 0 or occupancy < self.pressure_kill_at:
            return None
        with self._lock:
            live = [
                (qid, reg)
                for qid, reg in self._active.items()
                if qid not in self._killed
            ]
            if not live:
                return None
            qid, _reg = max(live, key=lambda kv: (-kv[1]["priority"], kv[1]["reserved"]))
            return self._kill_locked(
                qid, f"memory pressure: reservations at {occupancy:.0%} of budget"
            )

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "activeQueries": len(self._active),
                "kills": [r.to_dict() for r in self.kill_log],
            }


# ---------------------------------------------------------------------------
# graceful degradation
# ---------------------------------------------------------------------------
# process-wide pressure level: the serving broker's degradation controller
# publishes here so engine-layer consumers (macro-batch pipeline depth in
# parallel/engine.py) can react without holding a reference to the governor
_PRESSURE_LEVEL = 0
_PRESSURE_LOCK = threading.Lock()


def _set_process_pressure(level: int) -> None:
    global _PRESSURE_LEVEL
    with _PRESSURE_LOCK:
        _PRESSURE_LEVEL = int(level)


def current_pressure_level() -> int:
    with _PRESSURE_LOCK:
        return _PRESSURE_LEVEL


def pipeline_depth_under_pressure(depth: int, level: Optional[int] = None) -> int:
    """Macro-batch pipeline depth under pressure: every level past 1 drops
    one in-flight launch (floor 1), and level 3 serializes outright — each
    launch holds a capture copy of its batch inputs, so shrinking depth
    directly sheds resident HBM."""
    lvl = current_pressure_level() if level is None else int(level)
    if lvl >= 3:
        return 1
    return max(1, int(depth) - max(0, lvl - 1))


class DegradationController:
    """Progressive load shedding driven by one occupancy signal in [0, 1]
    (max of reservation occupancy and admission-bucket deficit):

      level 1 (>= 0.70): broker result cache disabled (stop retaining
              bytes), low-priority queries shed immediately;
      level 2 (>= 0.85): macro-batch pipeline depth shrinks by one
              (one less in-flight capture copy in HBM);
      level 3 (>= 0.95): pipeline fully serialized; the watchdog's
              pressure patrol may start killing.

    Published as the admission.pressureLevel gauge and (when > 0) a span
    annotation on every served query's plan span."""

    THRESHOLDS = ((0.70, 1), (0.85, 2), (0.95, 3))

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._level = 0

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def update(self, occupancy: float) -> int:
        lvl = 0
        for threshold, candidate in self.THRESHOLDS:
            if occupancy >= threshold:
                lvl = candidate
        # the autopilot's degrade_level knob is a FLOOR: on sustained SLO
        # breach the controller can hold the ladder up even when memory
        # occupancy alone would not (ISSUE 18: breach-driven degradation)
        from pinot_tpu.cluster import autopilot

        lvl = max(lvl, int(autopilot.knobs().get("degrade_level")))
        with self._lock:
            self._level = lvl
        METRICS.gauge("admission.pressureLevel").set(float(lvl))
        _set_process_pressure(lvl)
        return lvl

    def result_cache_enabled(self) -> bool:
        return self.level < 1

    def shed_low_priority(self) -> bool:
        return self.level >= 1

    def pipeline_depth(self, depth: int) -> int:
        return pipeline_depth_under_pressure(depth, self.level)


# ---------------------------------------------------------------------------
# process-wide host-memory ledger (caches + in-flight queries, one budget)
# ---------------------------------------------------------------------------
_HOST_BUDGET: Optional[ResourceBudget] = None
_HOST_BUDGET_LOCK = threading.Lock()


def process_host_budget() -> ResourceBudget:
    """The one host-memory ledger per process: broker result caches,
    compiled-plan caches, and in-flight query working sets all charge it
    (PINOT_TPU_HOST_BUDGET_BYTES, default 1 GiB).  Before r11 each cache
    bounded itself independently, so caches + queries could jointly
    overcommit host memory."""
    global _HOST_BUDGET
    with _HOST_BUDGET_LOCK:
        if _HOST_BUDGET is None:
            _HOST_BUDGET = ResourceBudget(
                int(os.environ.get("PINOT_TPU_HOST_BUDGET_BYTES", str(1 << 30))),
                gauge="admission.hostReservedBytes",
            )
        return _HOST_BUDGET


def default_server_hbm_budget() -> int:
    """Per-server HBM reservation budget (0 disables reservation tracking)."""
    return int(os.environ.get("PINOT_TPU_SERVER_HBM_BUDGET_BYTES", str(8 << 30)))


# ---------------------------------------------------------------------------
# governor facade
# ---------------------------------------------------------------------------
class AdmissionGrant:
    """Handle for one admitted query's resources: close() releases the host
    reservation and deregisters from the watchdog (idempotent — exception
    paths and the happy path both land here)."""

    __slots__ = ("_governor", "query_id", "_ticket", "_closed")

    def __init__(self, governor: "ResourceGovernor", query_id: str, ticket: Optional[int]):
        self._governor = governor
        self.query_id = query_id
        self._ticket = ticket
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._governor._finish(self.query_id, self._ticket)


class ResourceGovernor:
    """One serving broker's resource-governance stack: token-bucket
    admission + host-memory ledger + watchdog + degradation, composed so a
    single admit()/close() pair brackets every served query.  Defaults are
    permissive (admission off, ample budgets) — deployments opt in via the
    PINOT_TPU_ADMISSION_* / *_BUDGET_BYTES environment knobs or by
    constructing the parts explicitly."""

    def __init__(
        self,
        admission: Optional[AdmissionController] = None,
        host_budget: Optional[ResourceBudget] = None,
        watchdog: Optional[QueryWatchdog] = None,
        degrade: Optional[DegradationController] = None,
    ):
        if admission is None:
            admission = AdmissionController(
                rate_units_per_s=float(os.environ.get("PINOT_TPU_ADMISSION_RATE", "0")),
                burst_units=(
                    float(os.environ["PINOT_TPU_ADMISSION_BURST"])
                    if "PINOT_TPU_ADMISSION_BURST" in os.environ
                    else None
                ),
                max_queue=int(os.environ.get("PINOT_TPU_ADMISSION_QUEUE", "8")),
                knob="admission_rate",
            )
        if watchdog is None:
            watchdog = QueryWatchdog(
                runaway_ms=float(os.environ.get("PINOT_TPU_RUNAWAY_MS", "0")),
                pressure_kill_at=float(os.environ.get("PINOT_TPU_PRESSURE_KILL_AT", "0")),
            )
        self.admission = admission
        self.host_budget = host_budget if host_budget is not None else process_host_budget()
        self.watchdog = watchdog
        self.degrade = degrade if degrade is not None else DegradationController()

    @staticmethod
    def priority_of(ctx: QueryContext) -> int:
        """queryPriority option (int; negative = sheddable) with the r5
        isSecondaryWorkload contract folded in as the low tier."""
        v = ctx.options.get("queryPriority")
        if v is not None:
            try:
                return int(v)
            except (TypeError, ValueError):
                METRICS.counter("admission.badPriorityOption").inc()
                return 0
        sec = ctx.options.get("isSecondaryWorkload")
        return -1 if str(sec).lower() in ("1", "true", "yes") else 0

    def _occupancy(self) -> float:
        return max(self.host_budget.occupancy(), self.admission.deficit())

    def admit(
        self,
        query_id: str,
        ctx: QueryContext,
        cost: QueryCost,
        deadline: Optional[Deadline] = None,
    ) -> AdmissionGrant:
        """Full admission for one query: degradation update, priority shed,
        token charge, host reservation, watchdog registration, pressure
        patrol.  Raises TooManyRequestsError (shed) or ReservationError
        (no capacity) — both carry the query id."""
        priority = self.priority_of(ctx)
        self.degrade.update(self._occupancy())
        if priority < 0 and self.degrade.shed_low_priority():
            METRICS.counter("admission.shed").inc()
            raise TooManyRequestsError(
                f"query {query_id}: low-priority query shed under pressure "
                f"(level {self.degrade.level})",
                query_id=query_id,
            )
        self.admission.admit(query_id, units=cost.units, priority=priority, deadline=deadline)
        ticket = self.host_budget.reserve(
            cost.host_bytes, what="query working set", query_id=query_id
        )
        try:
            runaway = ctx.options.get("maxRuntimeMs")
            self.watchdog.register(
                query_id,
                reserved_bytes=cost.host_bytes + cost.hbm_bytes,
                priority=priority,
                runaway_ms=float(runaway) if runaway is not None else None,
            )
            level = self.degrade.update(self._occupancy())
            if level >= 3:
                self.watchdog.patrol(self.host_budget.occupancy())
            return AdmissionGrant(self, query_id, ticket)
        except BaseException:
            # unwind the half-built grant: an exception past the reserve
            # would otherwise leak the host-budget ticket and (after
            # register) a phantom watchdog entry; deregister is idempotent
            self._finish(query_id, ticket)
            raise

    def _finish(self, query_id: str, ticket: Optional[int]) -> None:
        if ticket is not None:
            self.host_budget.release(ticket)
        self.watchdog.deregister(query_id)
        self.degrade.update(self._occupancy())

    def cancel_probe(self, query_id: str) -> Callable[[], Optional[str]]:
        return self.watchdog.cancel_probe(query_id)

    def try_charge_hedge(self, units: float = 1.0) -> bool:
        """Non-blocking token charge for a hedged backup launch.  A hedge is
        strictly optional work, so it may only take tokens that are free
        RIGHT NOW — it never queues, never sheds, and under pressure loses
        to primaries (which can still wait for refill)."""
        return self.admission.try_charge(units)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready state behind GET /debug/admission + `cli admission`."""
        return {
            "pressureLevel": self.degrade.level,
            "admission": self.admission.snapshot(),
            "hostBudget": self.host_budget.snapshot(),
            "watchdog": self.watchdog.snapshot(),
        }
