"""HTTP query endpoint: the broker REST surface.

Reference parity: Pinot's broker query REST (POST /query/sql handled by
BaseSingleStageBrokerRequestHandler) + cursor endpoints + /health and
/metrics (JSON, or Prometheus text with ?format=prometheus) and the
/debug/queries slow-query surface.  Re-design: stdlib http.server on a daemon thread serving an
in-process QueryEngine or cluster Broker — the data plane stays in-process
(SURVEY.md §2.6); this surface exists for clients/tools parity.

Response shape follows BrokerResponse: {"resultTable": {"dataSchema":
{"columnNames": [...]}, "rows": [...]}, "numDocsScanned": ..., ...}.
"""
from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from pinot_tpu.query.cursors import ResponseStore
from pinot_tpu.query.result import ResultTable
from pinot_tpu.utils.metrics import METRICS


def _jsonable(v):
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and (v != v or v in (float("inf"), float("-inf"))):
        return None
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    return v


def broker_response(result: ResultTable) -> Dict[str, Any]:
    s = result.stats
    return {
        "resultTable": {
            "dataSchema": {"columnNames": list(result.columns)},
            "rows": [[_jsonable(v) for v in row] for row in result.rows],
        },
        "numRowsResultSet": len(result.rows),
        "numDocsScanned": s.num_docs_scanned,
        "numSegmentsQueried": s.num_segments_queried,
        "numSegmentsPruned": s.num_segments_pruned,
        "numSegmentsProcessed": s.num_segments_processed,
        "totalDocs": s.total_docs,
        "timeUsedMs": round(s.time_ms, 3),
        "requestId": s.query_id,
        "trace": s.trace,
        # fault surface (BrokerResponse partialResult / processingExceptions)
        "partialResult": bool(s.partial_result),
        "exceptions": list(s.exceptions),
        "numServersQueried": s.num_servers_queried,
        "numServersResponded": s.num_servers_responded,
    }


class QueryServer:
    """Serves one engine-like object (anything with .sql or .query)."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0):
        self.engine = engine
        self.cursors = ResponseStore()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code: int, text: str, content_type: str) -> None:
                body = text.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    url = urllib.parse.urlsplit(self.path)
                    qs = urllib.parse.parse_qs(url.query)
                    if url.path == "/health":
                        self._send(200, {"status": "OK"})
                    elif url.path == "/metrics":
                        # a broker engine federates its servers' registries
                        # into one labeled cluster exposition; plain engines
                        # fall back to this process's registry
                        fed = getattr(outer.engine, "federated_prometheus", None)
                        if qs.get("format", [""])[0] == "prometheus":
                            self._send_text(
                                200,
                                fed() if fed is not None else METRICS.to_prometheus(),
                                "text/plain; version=0.0.4; charset=utf-8",
                            )
                        else:
                            snap = METRICS.snapshot()
                            fed_json = getattr(outer.engine, "federated_snapshot", None)
                            if fed_json is not None:
                                snap["servers"] = fed_json()
                            self._send(200, snap)
                    elif url.path == "/debug/queries":
                        slow = getattr(outer.engine, "slow_queries", None)
                        if slow is None:
                            self._send(404, {"error": "engine has no slow-query log"})
                            return
                        limit = int(qs.get("limit", ["0"])[0]) or None
                        self._send(200, {"queries": slow.snapshot(limit)})
                    elif url.path == "/debug/admission":
                        gov = getattr(outer.engine, "governor", None)
                        if gov is None:
                            self._send(404, {"error": "engine has no resource governor"})
                            return
                        self._send(200, gov.snapshot())
                    elif url.path == "/debug/perf":
                        # per-table/per-shape perf ledger (utils/perf.py):
                        # rolling rows/s, bytes/s, roofline %, compile ms,
                        # plan-cache outcomes, QPS — the `cli perf` source
                        snap_fn = getattr(outer.engine, "perf_snapshot", None)
                        if snap_fn is not None:
                            self._send(200, snap_fn())
                        else:
                            from pinot_tpu.utils.perf import PERF_LEDGER

                            self._send(200, PERF_LEDGER.snapshot())
                    elif url.path == "/debug/autopilot":
                        # SLO autopilot view: knob values vs clamp bounds,
                        # last N controller decisions with triggering signal,
                        # per-table SLO state (cluster/autopilot.py)
                        snap_fn = getattr(outer.engine, "autopilot_snapshot", None)
                        if snap_fn is None:
                            self._send(404, {"error": "engine has no autopilot view"})
                            return
                        self._send(200, snap_fn())
                    elif url.path == "/debug/election":
                        # coordinator HA view: current leader + per-candidate
                        # lease/epoch/role state (cluster/election.py)
                        snap_fn = getattr(outer.engine, "election_snapshot", None)
                        if snap_fn is None:
                            self._send(404, {"error": "engine has no election view"})
                            return
                        self._send(200, snap_fn())
                    elif url.path.startswith("/cursors/"):
                        parts = url.path.strip("/").split("/")
                        cid = parts[1]
                        page = int(parts[2]) if len(parts) > 2 else 0
                        self._send(200, outer.cursors.fetch(cid, page))
                    else:
                        self._send(404, {"error": f"unknown path {self.path}"})
                except KeyError as e:
                    self._send(404, {"error": str(e)})
                except Exception as e:  # noqa: BLE001 - boundary
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if self.path not in ("/query/sql", "/query"):
                        self._send(404, {"error": f"unknown path {self.path}"})
                        return
                    sql = req.get("sql", "")
                    run = getattr(outer.engine, "sql", None) or outer.engine.query
                    result = run(sql)
                    payload = broker_response(result)
                    if req.get("useCursor"):
                        cid = outer.cursors.register(result, int(req.get("pageSize", 1000)))
                        payload["cursorId"] = cid
                        payload["resultTable"]["rows"] = payload["resultTable"]["rows"][
                            : int(req.get("pageSize", 1000))
                        ]
                    self._send(200, payload)
                except Exception as e:  # noqa: BLE001 - boundary
                    from pinot_tpu.analysis.plan_check import PlanCheckError
                    from pinot_tpu.cluster.admission import (
                        QueryKilledError,
                        ReservationError,
                        TooManyRequestsError,
                    )
                    from pinot_tpu.cluster.broker import (
                        NoReplicaAvailableError,
                        QuotaExceededError,
                        ScatterGatherError,
                    )
                    from pinot_tpu.cluster.election import NotLeaderError
                    from pinot_tpu.query.safety import AdmissionError, QueryTimeoutError

                    if isinstance(e, NotLeaderError):
                        # control-plane leadership moved and the bounded
                        # failover park expired: retryable 503 — the standby
                        # finishes taking over and the next attempt serves
                        self._send(503, {"error": str(e), "errorCode": "NOT_LEADER"})
                    elif isinstance(e, QuotaExceededError):
                        # the reference's 429 QUERY_QUOTA_EXCEEDED contract:
                        # throttled clients must be able to back off
                        self._send(429, {"error": str(e), "errorCode": "QUERY_QUOTA_EXCEEDED"})
                    elif isinstance(e, TooManyRequestsError):
                        # admission shed: over the cost-rate budget, rejected
                        # up front with the minted query id for correlation
                        self._send(
                            429,
                            {
                                "error": str(e),
                                "errorCode": "TOO_MANY_REQUESTS_ERROR",
                                "requestId": e.query_id,
                            },
                        )
                    elif isinstance(e, QueryKilledError):
                        # watchdog killed it mid-flight and the query did not
                        # allow partial results: retryable 503 with the reason
                        self._send(
                            503,
                            {
                                "error": str(e),
                                "errorCode": "QUERY_KILLED",
                                "requestId": e.query_id,
                                "reason": e.reason,
                            },
                        )
                    elif isinstance(e, ReservationError):
                        # HBM/host reservation refused: the tier is at
                        # capacity RIGHT NOW — retryable as queries drain.
                        # Checked before the AdmissionError base class below.
                        self._send(
                            503,
                            {
                                "error": str(e),
                                "errorCode": "SERVER_OUT_OF_CAPACITY",
                                "requestId": e.query_id,
                            },
                        )
                    elif isinstance(e, QueryTimeoutError):
                        # deadline blew anywhere in the scatter: 408, the
                        # reference's EXECUTION_TIMEOUT_ERROR contract
                        self._send(408, {"error": str(e), "errorCode": "EXECUTION_TIMEOUT_ERROR"})
                    elif isinstance(e, AdmissionError):
                        # resource admission refused up-front: retryable 503
                        self._send(
                            503,
                            {"error": str(e), "errorCode": "SERVER_RESOURCE_LIMIT_EXCEEDED"},
                        )
                    elif isinstance(e, ScatterGatherError):
                        # every replica of some segment failed and the query
                        # did not allow partial results
                        self._send(
                            500,
                            {
                                "error": str(e),
                                "errorCode": "SERVER_SCATTER_ERROR",
                                "exceptions": e.exceptions,
                            },
                        )
                    elif isinstance(e, NoReplicaAvailableError):
                        # a segment lost every live replica: retryable 503
                        # (capacity may come back), distinct from scatter
                        # failures so clients can tell "down" from "flaky"
                        self._send(503, {"error": str(e), "errorCode": "NO_REPLICA_AVAILABLE"})
                    elif isinstance(e, PlanCheckError):
                        # statically-rejected plan: a 400 with the machine
                        # code, never a tracer traceback (analysis/plan_check)
                        self._send(400, e.to_dict())
                    else:
                        self._send(500, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "QueryServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class PinotClient:
    """Minimal python client over the REST surface (pinot-java-client /
    pinotdb analog)."""

    def __init__(self, url: str):
        self.url = url.rstrip("/")

    def execute(self, sql: str, **kw) -> Dict[str, Any]:
        import urllib.request

        body = json.dumps({"sql": sql, **kw}).encode("utf-8")
        req = urllib.request.Request(
            self.url + "/query/sql", data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def fetch_cursor(self, cursor_id: str, page: int) -> Dict[str, Any]:
        import urllib.request

        with urllib.request.urlopen(f"{self.url}/cursors/{cursor_id}/{page}") as resp:
            return json.loads(resp.read().decode("utf-8"))
