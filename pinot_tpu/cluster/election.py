"""Lease-based coordinator leadership: durable lease, fenced epochs, failover.

Reference parity: the controller leader election Pinot delegates to Helix
(ZooKeeper ephemeral-node leadership + the cluster's epoch'd external view).
Re-design for the ZK-free control plane: leadership is a DURABLE LEASE FILE
in the coordinator's meta_dir ({meta_dir}/lease.json, written with the same
tmp-fsync-replace discipline as every other durability artifact) carrying a
monotonically increasing **epoch** — the fencing token.  Taurus (PAPERS.md,
"Near Data Processing in Taurus Database") makes the same move: the durable
log IS the database, and availability comes from fencing who may write it,
not from any process staying up.

The pieces:

  * LeaseManager — acquire/renew/release over the lease file on an
    INJECTABLE clock (tests and the bench drive a simulated clock; W022
    lints wall-clock arithmetic out of lease code).  Every acquisition bumps
    the epoch; a polite acquire refuses an unexpired lease held by another
    node, while the boot-time force acquire models the operator restarting a
    coordinator over its own meta_dir (the restart FENCES the zombie: the
    old in-memory object keeps a stale epoch and can no longer commit).
  * The epoch fence — cluster/journal.py stamps every append with the
    writer's epoch and calls LeaseManager.validate_writer() under the
    journal lock: when the durable lease has moved past the writer's epoch,
    the append raises FencedEpochError BEFORE any byte reaches the log.  A
    GC-paused leader that wakes past lease expiry can therefore not commit,
    and replay additionally drops any epoch-regressed interleaving.
  * JournalFollower — a standby coordinator's read-only incremental view of
    the leader's journal, riding the shared spi.filesystem.TailFollower
    (byte-offset memo + torn-tail park, the same follower FileStream ingest
    tails with).  Compactions (journal truncations) resynchronize from the
    snapshot.  The standby never writes or sweeps the leader's directory.
  * CoordinatorHandle — what brokers and servers hold INSTEAD of a raw
    Coordinator.  Attribute reads delegate to the current leader (falling
    back to the last known leader's versioned routing view during a
    failover, so the data plane keeps serving); control-plane method calls
    catch NotLeaderError, re-resolve leadership with bounded jittered
    retries, park bounded (reserve_or_wait-style) while a standby takes
    over, and re-register live-change listeners and server instances on the
    new leader.

Split-brain determinism: two standbys racing an expired lease both bump to
the same epoch; the one whose durable write lost discovers the foreign
holder at its next fence check and demotes — the journal never interleaves
epochs (tests/test_leader_election.py proves this under the kill-point
harness).
"""
from __future__ import annotations

import json
import logging
import os
import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from pinot_tpu.utils import threads
from pinot_tpu.spi.filesystem import TailFollower, durable_write_json, sweep_tmp
from pinot_tpu.utils.crashpoints import crash_point
from pinot_tpu.utils.metrics import METRICS

log = logging.getLogger("pinot_tpu.cluster")

LEASE_FILE = "lease.json"


class NotLeaderError(RuntimeError):
    """A control-plane mutation reached a coordinator that is not the
    current leader (standby, paused, or deposed).  CoordinatorHandle
    catches this, re-resolves leadership, and retries bounded."""

    def __init__(self, message: str, leader_hint: Optional[str] = None):
        super().__init__(message)
        self.leader_hint = leader_hint


class FencedEpochError(NotLeaderError):
    """The epoch fence tripped: the durable lease moved past this writer's
    epoch, so its journal append was REFUSED before any byte hit the log.
    Subclasses NotLeaderError so the handle's failover retry covers it."""

    def __init__(self, node: str, epoch: int, lease_epoch: int, holder: str):
        super().__init__(
            f"journal append fenced: {node} holds epoch {epoch} but the lease "
            f"moved to {holder!r} at epoch {lease_epoch}",
            leader_hint=holder,
        )
        self.node = node
        self.epoch = epoch
        self.lease_epoch = lease_epoch
        self.holder = holder


@dataclass(frozen=True)
class Lease:
    holder: str
    epoch: int
    expires_at: float
    acquired_at: float

    def to_dict(self) -> Dict[str, Any]:
        return {
            "holder": self.holder,
            "epoch": self.epoch,
            "expiresAt": self.expires_at,
            "acquiredAt": self.acquired_at,
        }


def _quarantine(path: str) -> Optional[str]:
    """Rename a corrupt file aside (never delete evidence)."""
    for i in range(1000):
        aside = f"{path}.corrupt-{i}"
        if not os.path.exists(aside):
            try:
                os.replace(path, aside)
                return aside
            except OSError:
                log.exception("could not quarantine corrupt file %s", path)
                return None
    return None


class LeaseManager:
    """The durable lease over one meta_dir.

    Clock discipline: `clock` is injectable and defaults to time.monotonic —
    lease deadlines and expiry comparisons NEVER touch the wall clock (an
    NTP step must not depose a healthy leader or immortalize a dead one;
    repo_lint W022 enforces this).  Production deployments with separate
    hosts would fold bounded clock skew into the TTL margin; the FaultPlan
    lease_clock_skew rule models exactly that."""

    def __init__(
        self,
        meta_dir: str,
        node_id: str,
        ttl_s: Optional[float] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.meta_dir = meta_dir
        self.node_id = node_id
        self.ttl_s = float(
            os.environ.get("PINOT_TPU_LEASE_TTL_S", "5") if ttl_s is None else ttl_s
        )
        self.clock = clock or time.monotonic
        # cluster/faults.py hooks: renew suppression (leader-pause) and
        # per-node clock skew ride the plan when one is attached
        self.fault_plan = None
        self.epoch = 0  # the epoch THIS node last held (0 = never led)
        self.is_leader = False
        self._lock = threads.Lock()
        os.makedirs(meta_dir, exist_ok=True)

    @property
    def lease_path(self) -> str:
        return os.path.join(self.meta_dir, LEASE_FILE)

    def now(self) -> float:
        """This node's view of cluster time: the injectable clock plus any
        fault-injected skew (lease_clock_skew rule)."""
        t = self.clock()
        plan = self.fault_plan
        if plan is not None:
            t += plan.lease_skew_ms(self.node_id) / 1000.0
        return t

    def sweep_stale_tmp(self) -> List[str]:
        """Sweep the lease/meta dir of stale `*.tmp` artifacts a crash
        mid-acquire left behind — a lease.json.tmp is by definition an
        UNCOMMITTED acquisition and must never be mistaken for a live
        lease.  Runs on coordinator boot and on standby promote."""
        swept = sweep_tmp(self.meta_dir)
        stale = [p for p in swept if os.path.basename(p).startswith(LEASE_FILE)]
        if stale:
            METRICS.counter("coordinator.staleLeaseTmpSwept").inc(len(stale))
            log.warning("swept stale lease tmp artifacts: %s", stale)
        return swept

    def read(self) -> Optional[Lease]:
        """The durable lease as committed on disk (None when absent; a
        corrupt lease quarantines aside and reads as absent — an unreadable
        lease must not wedge the election forever)."""
        path = self.lease_path
        if not os.path.exists(path):
            return None
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            return Lease(
                holder=str(doc["holder"]),
                epoch=int(doc["epoch"]),
                expires_at=float(doc["expiresAt"]),
                acquired_at=float(doc["acquiredAt"]),
            )
        except (json.JSONDecodeError, OSError, KeyError, TypeError, ValueError) as e:
            METRICS.counter("coordinator.leaseCorrupt").inc()
            aside = _quarantine(path)
            log.warning("corrupt lease %s (%s) quarantined to %s", path, e, aside)
            return None

    def _write(self, lease: Lease, crash_prefix: str) -> None:
        durable_write_json(self.lease_path, lease.to_dict(), crash_prefix=crash_prefix)

    def try_acquire(self, force: bool = False) -> bool:
        """Acquire leadership, bumping the epoch.  Polite by default: an
        unexpired lease held by another node refuses.  `force=True` is the
        boot-time takeover — a coordinator (re)started over its meta_dir
        claims the directory and fences any zombie still holding the old
        epoch in memory."""
        with self._lock:
            cur = self.read()
            crash_point("election.acquire.after_read")
            now = self.now()
            if (
                cur is not None
                and cur.holder != self.node_id
                and not force
                and cur.expires_at > now
            ):
                return False
            epoch = (cur.epoch if cur is not None else 0) + 1
            self._write(
                Lease(self.node_id, epoch, now + self.ttl_s, now),
                crash_prefix="election.acquire",
            )
            self.epoch = epoch
            self.is_leader = True
            METRICS.counter("coordinator.leaderElections").inc()
            METRICS.gauge("coordinator.epoch").set(epoch)
            return True

    def renew(self) -> bool:
        """Extend the held lease.  Returns False when leadership is LOST
        (the durable lease moved past this node's epoch) — the caller must
        demote.  A FaultPlan leader-pause suppresses the renewal entirely
        (the frozen process never runs it), returning True unchanged: the
        danger of that lie is exactly what the epoch fence catches."""
        with self._lock:
            if not self.is_leader:
                return False
            plan = self.fault_plan
            if plan is not None and not plan.allow_lease_renew(self.node_id):
                return True  # frozen: the renewal simply never happened
            cur = self.read()
            if cur is None or cur.holder != self.node_id or cur.epoch != self.epoch:
                self.is_leader = False
                METRICS.counter("coordinator.leadershipLost").inc()
                return False
            now = self.now()
            # the write itself carries the kill-points (election.renew
            # .after_write / .after_replace) — no extra point here, a
            # duplicate name would fire inside the write instead
            self._write(
                Lease(self.node_id, self.epoch, now + self.ttl_s, cur.acquired_at),
                crash_prefix="election.renew",
            )
            METRICS.counter("coordinator.leaseRenewals").inc()
            return True

    def release(self) -> None:
        """Voluntary step-down: expire the held lease NOW so a standby can
        take over without waiting out the TTL."""
        with self._lock:
            if not self.is_leader:
                return
            cur = self.read()
            if cur is not None and cur.holder == self.node_id and cur.epoch == self.epoch:
                self._write(
                    Lease(self.node_id, self.epoch, self.now(), cur.acquired_at),
                    crash_prefix="election.release",
                )
            self.is_leader = False

    def expired(self) -> bool:
        """Whether the durable lease is absent or past expiry on this
        node's clock (the standby's promotion predicate)."""
        cur = self.read()
        return cur is None or cur.expires_at <= self.now()

    # -- the epoch fence (called by MetaJournal.append under ITS lock) ----
    def validate_writer(self) -> int:
        """Refuse the write when the durable lease moved past this node's
        epoch; returns the epoch to stamp on the entry otherwise.  Note the
        epoch-EQUAL-but-foreign-holder case: two racing acquisitions of an
        expired lease both bump to N+1, and the loser (whose durable write
        was overwritten) discovers the foreign holder here."""
        crash_point("journal.append.before_fence")
        with self._lock:
            if not self.is_leader:
                raise NotLeaderError(f"{self.node_id} is not the leader")
            cur = self.read()
            if cur is not None and (
                cur.epoch > self.epoch
                or (cur.epoch == self.epoch and cur.holder != self.node_id)
            ):
                self.is_leader = False
                raise FencedEpochError(self.node_id, self.epoch, cur.epoch, cur.holder)
            epoch = self.epoch
        crash_point("journal.append.after_fence")
        return epoch

    def snapshot(self) -> Dict[str, Any]:
        lease = self.read()
        with self._lock:
            epoch, is_leader = self.epoch, self.is_leader
        return {
            "node": self.node_id,
            "epoch": epoch,
            "isLeader": is_leader,
            "ttl_s": self.ttl_s,
            "lease": None
            if lease is None
            else {
                "holder": lease.holder,
                "epoch": lease.epoch,
                "expiresIn_s": round(lease.expires_at - self.now(), 3),
            },
        }


class JournalFollower:
    """A standby coordinator's read-only incremental view of the leader's
    journal: snapshot bootstrap/resync + TailFollower over journal.jsonl.
    Never writes, never sweeps, never quarantines — the directory belongs
    to the leader; a torn tail parks (it may be an append IN FLIGHT)."""

    def __init__(self, meta_dir: str):
        from pinot_tpu.cluster.journal import JOURNAL_FILE, SNAPSHOT_FILE

        self.meta_dir = meta_dir
        self._snapshot_path = os.path.join(meta_dir, SNAPSHOT_FILE)
        self._tail = TailFollower(os.path.join(meta_dir, JOURNAL_FILE))
        self.last_seq = 0
        self.max_epoch = 0

    def _read_snapshot(self) -> Tuple[Optional[Dict[str, Any]], int]:
        for path in (self._snapshot_path, self._snapshot_path + ".bak"):
            if not os.path.exists(path):
                continue
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
                return doc.get("state") or {}, int(doc.get("seq", 0))
            except (json.JSONDecodeError, OSError, ValueError, TypeError):
                # mid-compaction read or corruption: the leader's own load()
                # quarantines on restart — a follower just tries the .bak
                METRICS.counter("coordinator.standbySnapshotRetries").inc()
        return None, 0

    def bootstrap(self) -> Optional[Dict[str, Any]]:
        """Initial sync: position after the snapshot (if any) and return its
        state for the standby to apply before the first poll()."""
        state, snap_seq = self._read_snapshot()
        self.last_seq = snap_seq
        return state

    def poll(self) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]]]:
        """Pull newly committed entries.  Returns (resync_state, entries):
        `resync_state` is non-None when the journal was truncated under the
        follower (a leader compaction) — the caller must RESET to that
        snapshot state before applying the entries."""
        lines, _next, _eof, truncated = self._tail.read()
        state: Optional[Dict[str, Any]] = None
        if truncated:
            state, snap_seq = self._read_snapshot()
            state = state or {}
            self.last_seq = snap_seq
            self.max_epoch = 0  # epochs re-ratchet over the fresh tail
            # the shrink read reset the tail without surfacing lines:
            # re-read from the top so post-compaction entries apply NOW
            lines, _next, _eof, _tr = self._tail.read()
        entries: List[Dict[str, Any]] = []
        for _i, text in lines:
            text = text.strip()
            if not text:
                continue
            try:
                entry = json.loads(text)
                seq = int(entry["seq"])
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                # a complete-but-unparseable line mid-journal: skip it and
                # count — the leader's restart load() owns quarantining
                METRICS.counter("coordinator.standbyJournalSkips").inc()
                continue
            if seq <= self.last_seq:
                continue  # already applied (snapshot overlap after resync)
            epoch = int(entry.get("epoch", 0) or 0)
            if epoch < self.max_epoch:
                # torn interleaving from a deposed epoch: replay ignores it
                METRICS.counter("coordinator.fencedReplayDropped").inc()
                continue
            if epoch > self.max_epoch:
                self.max_epoch = epoch
            self.last_seq = seq
            entries.append(entry)
        return state, entries


def _park_env_ms() -> float:
    return float(os.environ.get("PINOT_TPU_FAILOVER_PARK_MS", "10000"))


def _retries_env() -> int:
    return int(os.environ.get("PINOT_TPU_FAILOVER_RETRIES", "8"))


class CoordinatorHandle:
    """Leadership-aware facade brokers and servers hold instead of a raw
    Coordinator.

    Delegation contract:
      * attribute READS (`handle.tables`, `handle.live`, ...) resolve
        against the current leader, falling back to the LAST KNOWN leader
        during a failover window — the data plane keeps serving off that
        object's versioned routing view while control-plane leadership
        moves;
      * METHOD calls route to the current leader and, on NotLeaderError
        (standby hit, paused leader, epoch fence), re-resolve with bounded
        jittered retries and a bounded reserve_or_wait-style park while a
        standby takes over (run_election_tick is driven on every candidate
        during the park, so a single-threaded caller still converges);
      * on_live_change listeners and register_server instances are RECORDED
        and re-registered on every newly adopted leader, so breaker-heal
        wiring and membership survive the failover without any caller
        changes.
    """

    _INTERNAL = frozenset(
        {
            "_candidates",
            "_lock",
            "_last",
            "_adopted",
            "_listeners",
            "_servers",
            "_sleep",
            "_clock",
            "_rng",
            "park_ms",
            "retries",
            "auto_tick",
        }
    )

    # methods that are pure control-plane READS: they never park or retry —
    # during a failover they serve off the last known leader's versioned
    # view, exactly like the attribute-read path (the data plane must not
    # stall behind an election)
    _READ_METHODS = frozenset(
        {
            "external_view",
            "versioned_view",
            "_find_segment_object",
            "status_report",
            "election_state",
        }
    )

    def __init__(
        self,
        candidates,
        park_ms: Optional[float] = None,
        retries: Optional[int] = None,
        sleep: Optional[Callable[[float], None]] = None,
        clock: Optional[Callable[[], float]] = None,
        auto_tick: bool = True,
    ):
        if not candidates:
            raise ValueError("CoordinatorHandle needs at least one coordinator")
        self._candidates = list(candidates)
        self._lock = threads.RLock()
        self._last = None  # last adopted leader: the data-plane read fallback
        self._adopted: set = set()  # id()s of leaders already re-wired
        self._listeners: List[Any] = []  # on_live_change fns to re-register
        self._servers: Dict[str, Any] = {}  # name -> instance to re-register
        self._sleep = sleep or time.sleep
        self._clock = clock or time.monotonic
        self.park_ms = _park_env_ms() if park_ms is None else float(park_ms)
        self.retries = _retries_env() if retries is None else int(retries)
        self.auto_tick = auto_tick
        self._rng = random.Random(0x1EADE12)
        # adopt the boot-time leader so reads have a fallback from the start
        self.current()

    @classmethod
    def wrap(cls, coordinator) -> "CoordinatorHandle":
        """Idempotent: an existing handle passes through; a raw Coordinator
        becomes a single-candidate handle (standbys join via
        add_candidate)."""
        if isinstance(coordinator, CoordinatorHandle):
            return coordinator
        return cls([coordinator])

    def add_candidate(self, coordinator) -> "CoordinatorHandle":
        with self._lock:
            if coordinator not in self._candidates:
                self._candidates.append(coordinator)
        return self

    # -- resolution -------------------------------------------------------
    def _find_leader(self):
        with self._lock:
            cands = list(self._candidates)
        for c in cands:
            if getattr(c, "role", "leader") == "leader" and not getattr(c, "_paused", False):
                return c
        return None

    def current(self):
        """The current leader, adopting it (listener + server
        re-registration) when it changed — or None during a blackout."""
        leader = self._find_leader()
        if leader is None:
            return None
        with self._lock:
            if leader is not self._last:
                if id(leader) not in self._adopted:
                    self._adopt_locked(leader)
                    self._adopted.add(id(leader))
                self._last = leader
        return leader

    def _adopt_locked(self, leader) -> None:
        """Re-wire a newly resolved leader: servers re-register (idempotent
        — replayed membership reconciles, it does not re-journal) and
        live-change listeners re-subscribe, so broker breaker-heal paths
        keep working across the failover."""
        for server in list(self._servers.values()):
            try:
                leader.register_server(server)
            except Exception:  # noqa: BLE001 — adoption must not wedge resolution
                METRICS.counter("coordinator.handleAdoptErrors").inc()
                log.exception(
                    "re-registering server %s on new leader failed",
                    getattr(server, "name", "?"),
                )
        for fn in list(self._listeners):
            leader.on_live_change(fn)
        METRICS.counter("coordinator.handleLeadersAdopted").inc()

    def _current_for_read(self):
        leader = self.current()
        if leader is not None:
            return leader
        with self._lock:
            if self._last is not None:
                return self._last  # failover window: serve off the last routing view
            return self._candidates[0]

    # -- recorded registrations (re-played onto every new leader) ---------
    def on_live_change(self, fn) -> None:
        with self._lock:
            self._listeners.append(fn)
        # already adopted leaders got their listeners in _adopt_locked only
        # if registered before; register explicitly on the current one
        cur = self.current()
        if cur is not None:
            cur.on_live_change(fn)

    def register_server(self, server) -> None:
        with self._lock:
            self._servers[server.name] = server
        self._call("register_server", (server,), {})

    # -- failover-aware method dispatch -----------------------------------
    def _backoff_s(self, attempt: int) -> float:
        base = 0.005 * (2 ** min(attempt, 6))
        return base * (0.5 + self._rng.random())

    def _park_for_leader(self, deadline: float):
        """reserve_or_wait-style bounded park: a control-plane write waits a
        bounded window for a standby to take over instead of failing fast.
        Candidates' election ticks are driven here so a single-threaded
        process still converges (the standby promotes once the lease
        expires on the shared clock)."""
        attempt = 0
        while True:
            if self.auto_tick:
                with self._lock:
                    cands = list(self._candidates)
                for c in cands:
                    tick = getattr(c, "run_election_tick", None)
                    if tick is None:
                        continue
                    try:
                        tick()
                    except Exception:  # noqa: BLE001 — a sick candidate must not block the park
                        METRICS.counter("coordinator.handleTickErrors").inc()
                        log.exception("election tick failed during failover park")
            leader = self.current()
            if leader is not None:
                METRICS.counter("coordinator.failoverParksServed").inc()
                return leader
            if self._clock() >= deadline:
                METRICS.counter("coordinator.failoverParkTimeouts").inc()
                raise NotLeaderError(
                    "no coordinator leader within the failover park window"
                )
            attempt += 1
            self._sleep(self._backoff_s(attempt))

    def _call(self, name: str, args: tuple, kwargs: dict):
        deadline = self._clock() + self.park_ms / 1000.0
        attempt = 0
        while True:
            target = self.current()
            if target is None:
                target = self._park_for_leader(deadline)
            try:
                return getattr(target, name)(*args, **kwargs)
            except NotLeaderError:
                METRICS.counter("coordinator.notLeaderRetries").inc()
                attempt += 1
                if attempt > self.retries or self._clock() >= deadline:
                    raise
                # bounded jittered backoff before re-resolving (the W019
                # retry discipline, applied to the control plane)
                self._sleep(self._backoff_s(attempt))

    def election_snapshot(self) -> Dict[str, Any]:
        with self._lock:
            cands = list(self._candidates)
        leader = self._find_leader()
        return {
            "leader": getattr(leader, "node_id", None) if leader is not None else None,
            "candidates": [c.election_state() for c in cands],
        }

    # -- transparent delegation ------------------------------------------
    def __getattr__(self, name: str):
        if name.startswith("__") or name in CoordinatorHandle._INTERNAL:
            raise AttributeError(name)
        target = self._current_for_read()
        val = getattr(target, name)
        if callable(val):
            if name in CoordinatorHandle._READ_METHODS:
                def _read_call(*args, __name=name, **kwargs):
                    return getattr(self._current_for_read(), __name)(*args, **kwargs)

                _read_call.__name__ = name
                return _read_call

            def _failover_call(*args, __name=name, **kwargs):
                return self._call(__name, args, kwargs)

            _failover_call.__name__ = name
            return _failover_call
        return val
