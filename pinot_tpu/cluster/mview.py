"""Materialized views: time-bucketed pre-aggregations with query rewrite.

Reference parity: the fork's pinot-materialized-view module (17.7k LoC;
pinot-materialized-view/DESIGN.md) — MV definitions kept in cluster
metadata, minion refresh tasks per time bucket, watermark + STALE-bucket
invalidation, and broker query rewrite when the MV is fresh.

Re-design essentials kept: an MV is a real table whose segments are one per
time bucket; refresh re-runs the MV query per bucket through the ordinary
engine and swaps the bucket segment; freshness is per-bucket (the set of
source segments that fed the bucket's last refresh); the broker rewrites a
matching aggregate query onto the MV only when every touched bucket is
fresh — otherwise it silently falls back to the source table (same
contract as the reference's watermark check).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from pinot_tpu.cluster.broker import Broker
from pinot_tpu.cluster.coordinator import Coordinator
from pinot_tpu.query.ir import AggregationSpec, Expr, FilterOp, QueryContext
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.spi.config import SegmentsConfig, TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, FieldSpec, Schema

MS_DAY = 86_400_000

# source aggregation -> (mv column suffix, combine aggregation on the MV)
_AGG_MAP = {
    "count": ("count", "sum"),
    "sum": ("sum", "sum"),
    "min": ("min", "min"),
    "max": ("max", "max"),
}


@dataclass
class MaterializedView:
    name: str
    source_table: str
    dimensions: List[str]  # group columns (time column included if bucketed)
    metrics: List[Tuple[str, str]]  # (agg function, source column) — count uses ("count", "*")
    time_column: Optional[str] = None
    bucket_ms: int = MS_DAY
    # bucket id -> set of source segment names that fed the last refresh
    fresh: Dict[int, Set[str]] = field(default_factory=dict)

    def mv_column(self, func: str, col: str) -> str:
        return f"{func}_{'star' if col == '*' else col}"

    def mv_schema(self, source_schema: Schema) -> Schema:
        fields: List[FieldSpec] = []
        for d in self.dimensions:
            f = source_schema.field(d)
            fields.append(FieldSpec(d, f.data_type, role=f.role))
        for func, col in self.metrics:
            fields.append(FieldSpec(self.mv_column(func, col), DataType.DOUBLE, role=FieldRole.METRIC))
        return Schema(name=self.name, fields=fields)


class MaterializedViewManager:
    def __init__(self, coordinator: Coordinator, broker: Optional[Broker] = None):
        self.coordinator = coordinator
        self.broker = broker or Broker(coordinator)
        self.views: Dict[str, MaterializedView] = {}

    # -- definition ------------------------------------------------------
    def create_view(self, mv: MaterializedView) -> None:
        src = self.coordinator.tables[mv.source_table]
        if mv.time_column and mv.time_column not in mv.dimensions:
            raise ValueError("the MV time column must be one of its dimensions")
        schema = mv.mv_schema(src.schema)
        cfg = TableConfig(name=mv.name, segments=SegmentsConfig(time_column=mv.time_column))
        self.coordinator.add_table(schema, cfg)
        self.views[mv.name] = mv

    # -- freshness -------------------------------------------------------
    def _bucket_of(self, ms: int, mv: MaterializedView) -> int:
        return int(ms) // mv.bucket_ms

    def _source_segments_for_bucket(self, mv: MaterializedView, bucket: int) -> Set[str]:
        meta = self.coordinator.tables[mv.source_table]
        out: Set[str] = set()
        lo = bucket * mv.bucket_ms
        hi = lo + mv.bucket_ms
        for name, sm in meta.segment_meta.items():
            tr = sm.get("timeRange")
            if mv.time_column is None or tr is None or tr[0] is None:
                out.add(name)
            elif tr[0] < hi and tr[1] >= lo:
                out.add(name)
        return out

    def stale_buckets(self, view_name: str) -> List[int]:
        """Buckets whose CURRENT source segment set differs from the set at
        their last refresh (the STALE marking of the reference)."""
        mv = self.views[view_name]
        buckets = self._all_source_buckets(mv)
        return [b for b in buckets if self.views[view_name].fresh.get(b) != self._source_segments_for_bucket(mv, b)]

    def _all_source_buckets(self, mv: MaterializedView) -> List[int]:
        meta = self.coordinator.tables[mv.source_table]
        if mv.time_column is None:
            return [0]
        buckets: Set[int] = set()
        for sm in meta.segment_meta.values():
            tr = sm.get("timeRange")
            if tr is not None and tr[0] is not None:
                for b in range(self._bucket_of(tr[0], mv), self._bucket_of(tr[1], mv) + 1):
                    buckets.add(b)
        return sorted(buckets)

    # -- refresh (minion task analog) ------------------------------------
    def refresh(self, view_name: str) -> Dict[str, object]:
        mv = self.views[view_name]
        refreshed = []
        for bucket in self.stale_buckets(view_name):
            self._refresh_bucket(mv, bucket)
            refreshed.append(bucket)
        return {"view": view_name, "refreshedBuckets": refreshed}

    def _refresh_bucket(self, mv: MaterializedView, bucket: int) -> None:
        dims = ", ".join(mv.dimensions)
        aggs = ", ".join(
            f"{func}({col})" if func != "count" else "COUNT(*)" for func, col in mv.metrics
        )
        where = ""
        if mv.time_column is not None:
            lo = bucket * mv.bucket_ms
            hi = lo + mv.bucket_ms
            where = f" WHERE {mv.time_column} >= {lo} AND {mv.time_column} < {hi}"
        sql = (
            f"SELECT {dims}, {aggs} FROM {mv.source_table}{where} "
            f"GROUP BY {dims} LIMIT 10000000"
        )
        res = self.broker.query(sql)
        nd = len(mv.dimensions)
        data: Dict[str, np.ndarray] = {}
        for i, d in enumerate(mv.dimensions):
            data[d] = np.asarray([r[i] for r in res.rows], dtype=object)
        for j, (func, col) in enumerate(mv.metrics):
            data[mv.mv_column(func, col)] = np.asarray(
                [float(r[nd + j]) for r in res.rows], dtype=np.float64
            )
        seg_name = f"{mv.name}__b{bucket}"
        meta = self.coordinator.tables[mv.name]
        if seg_name in meta.ideal:  # replace the bucket's old segment
            for s in meta.ideal.pop(seg_name):
                if s in self.coordinator.servers:
                    self.coordinator.servers[s].drop_segment(mv.name, seg_name)
            meta.segment_meta.pop(seg_name, None)
        if len(res.rows):
            seg = build_segment(meta.schema, data, seg_name, table_config=meta.config)
            self.coordinator.add_segment(mv.name, seg)
        mv.fresh[bucket] = self._source_segments_for_bucket(mv, bucket)

    # -- broker rewrite ---------------------------------------------------
    def rewrite(self, ctx: QueryContext) -> Optional[QueryContext]:
        """Rewritten context onto a fresh matching MV, or None (fallback)."""
        for mv in self.views.values():
            if mv.source_table != ctx.table:
                continue
            new_ctx = self._try_rewrite(ctx, mv)
            if new_ctx is not None:
                return new_ctx
        return None

    def _try_rewrite(self, ctx: QueryContext, mv: MaterializedView) -> Optional[QueryContext]:
        if not ctx.group_by or ctx.extra_aggregations or ctx.having or ctx.set_ops:
            return None
        if not all(g.is_column and g.op in mv.dimensions for g in ctx.group_by):
            return None
        if ctx.filter is not None:
            for p in ctx.filter.predicates():
                if not (p.lhs.is_column and p.lhs.op in mv.dimensions):
                    return None
        available = {(f, c) for f, c in mv.metrics}
        new_select = []
        for s in ctx.select_list:
            if isinstance(s, AggregationSpec):
                if s.filter is not None or s.literal_args:
                    return None
                func = s.function
                col = "*" if s.expr is None else (s.expr.op if s.expr.is_column else None)
                if col is None or func not in _AGG_MAP or (func, col) not in available:
                    return None
                _, combine = _AGG_MAP[func]
                new_select.append(AggregationSpec(combine, Expr.col(mv.mv_column(func, col))))
            elif isinstance(s, Expr) and s.is_column and s.op in mv.dimensions:
                new_select.append(s)
            else:
                return None
        # freshness: every bucket the query could touch must be fresh
        if self.stale_buckets(mv.name):
            return None
        import dataclasses

        return dataclasses.replace(
            ctx,
            table=mv.name,
            select_list=new_select,
            select_aliases=list(ctx.select_aliases),
        )

    # -- query front door --------------------------------------------------
    def query(self, sql: str):
        """Broker query with MV rewrite (the reference's broker hook)."""
        from pinot_tpu.sql.parser import parse_query

        ctx = parse_query(sql)
        rewritten = self.rewrite(ctx)
        res = self.broker.execute(rewritten if rewritten is not None else ctx)
        res.stats.mv_rewrite = rewritten is not None  # type: ignore[attr-defined]
        return res
