"""TableRebalancer: live segment moves under query load.

Reference parity: TableRebalancer.rebalance (pinot-controller/.../rebalance/
TableRebalancer.java:201) and its availability contract (:122-134): during a
move a segment NEVER has fewer than `min_available_replicas` live serving
copies.  The mechanism is load-before-drop with a committed intermediate
state:

  1. LOAD  — every newly-desired replica materializes the segment (from a
             live peer's copy, or re-downloaded + CRC-verified from the
             deep store) and starts serving it;
  2. COMMIT — the new ideal state is journaled (fsync'd) and the routing
             view version bumps, so a coordinator crash on either side of
             the commit resolves to a consistent assignment on restart
             (before: old ideal, extra copies reconciled away; after: new
             ideal, stale copies reconciled away);
  3. DROP  — old replicas release only while the live copies among the
             committed assignment stay at or above the availability floor.

Kill-points `rebalance.after_add` and `rebalance.after_commit` sit between
the steps so the crash harness proves the ordering, and queries running
concurrently route on a consistent per-query snapshot of the view.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Set

from pinot_tpu.utils.crashpoints import crash_point
from pinot_tpu.utils.metrics import METRICS

log = logging.getLogger("pinot_tpu.cluster")


class TableRebalancer:
    def __init__(self, coordinator):
        self.coordinator = coordinator

    def rebalance(self, table: str, min_available_replicas: int = 1) -> Dict[str, int]:
        """Repair/redistribute `table`'s assignment over the CURRENT live
        set, one segment move at a time (each move independently satisfies
        the availability floor, so queries interleave safely)."""
        coord = self.coordinator
        meta = coord.tables[table]
        moved = added = dropped = 0
        for seg_name in list(meta.ideal):
            with coord._membership_lock:
                live = set(coord.live)
                servers = dict(coord.servers)
            current = set(meta.ideal.get(seg_name, ()))
            desired = set(coord._assign_for_rebalance(meta, seg_name))
            if desired == current:
                continue
            # -- 1. LOAD: materialize every new replica before anything drops
            placed: Set[str] = set()
            for s in sorted(desired - current):
                if self._materialize(table, seg_name, servers.get(s), current | live):
                    placed.add(s)
                    added += 1
            if (desired - current) - placed:
                # a target could not load the segment (no live copy, no deep
                # store): commit only the part of the move that materialized
                desired = (desired & current) | placed
                if desired == current:
                    continue
            crash_point("rebalance.after_add")
            # -- 2. COMMIT: availability floor decides whether old copies
            # may drop; the surviving assignment is journaled BEFORE drops
            survivors = {s for s in desired if s in live}
            if len(survivors) >= min_available_replicas:
                final = set(desired)
            else:
                final = set(desired) | current  # floor: keep the old copies
            coord._set_ideal(table, seg_name, final)
            crash_point("rebalance.after_commit")
            # -- 3. DROP: stale replicas release after the committed view
            # stopped routing to them
            for s in sorted(current - final):
                if s in servers:
                    servers[s].drop_segment(table, seg_name)
                    dropped += 1
            moved += 1
            METRICS.counter("coordinator.segmentsMoved").inc()
        return {"segmentsMoved": moved, "replicasAdded": added, "replicasDropped": dropped}

    def _materialize(self, table: str, seg_name: str, server, candidates) -> bool:
        """Make `server` serve the segment: share a live peer's object, or
        restore a CRC-verified copy from the deep store."""
        if server is None:
            return False
        if server.get_segment(table, seg_name) is not None:
            return True
        coord = self.coordinator
        segment = coord._find_segment_object(table, seg_name, candidates)
        if segment is not None:
            server.add_segment(table, segment)
            return True
        ds = coord.deep_store
        if ds is not None and ds.has_segment(table, seg_name):
            try:
                server.restore_segment(table, seg_name, ds)
                return True
            except Exception:  # noqa: BLE001 — a failed restore just skips this target
                METRICS.counter("coordinator.rebalanceRestoreFailures").inc()
                log.exception(
                    "rebalance: restoring %s/%s onto %s failed", table, seg_name, server.name
                )
        return False
