"""Cross-query micro-batcher: the broker-side coalescing stage of the
concurrent serving tier.

In-flight queries that share a batch key (the broker keys on
``(table, shape_fingerprint digest)``) wait up to a bounded window —
``PINOT_TPU_BATCH_WAIT_MS``, default 2 ms — for same-shape peers, then the
whole group executes as ONE vmapped plan launch (query/executor.py
``launch_segment_batch``).  A group also flushes immediately when it
reaches ``PINOT_TPU_BATCH_MAX`` members, so saturated load never waits.

Time is injectable: tests construct the batcher with a fake ``clock`` and
drive flushes deterministically through ``pump(now)`` — no real sleeps in
tier-1.  With the default wall clock a lazily started daemon worker wakes
on a condition variable at the earliest group deadline.  The worker/pump
path deliberately contains no blocking calls (no sleeps, no device fences,
no socket I/O — lint W018): the runner launches and collects device work,
but blocking ``Future.result()`` waits happen only in the submitting
caller threads.
"""

from __future__ import annotations

import os
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from pinot_tpu.utils import threads


def batch_wait_ms() -> float:
    """Bounded coalescing window; 0 disables batching (submit runs the
    query immediately as a singleton group).  Routed through the autopilot
    KnobRegistry: the env var is the initial value / clamp anchor, and a
    registry write takes effect on the next submit without rebuilding."""
    from pinot_tpu.cluster import autopilot

    return float(autopilot.knobs().get("batch_wait_ms"))


def batch_max() -> int:
    """Flush threshold — kept equal to the executor's vmap lane width so a
    full group maps 1:1 onto one batched launch."""
    return max(1, int(os.environ.get("PINOT_TPU_BATCH_MAX", "8")))


class BatchEntry:
    """One in-flight query waiting in a group: opaque broker payload plus
    the Future handed back to the submitter."""

    __slots__ = ("payload", "future")

    def __init__(self, payload: Any):
        self.payload = payload
        self.future: Future = threads.Future()


class _Group:
    __slots__ = ("entries", "deadline")

    def __init__(self, deadline: float):
        self.entries: List[BatchEntry] = []
        self.deadline = deadline


class MicroBatcher:
    """Coalesces submissions per key for a bounded wait, then hands the
    group to ``runner(entries)``.  The runner OWNS completion: it must
    resolve every entry's future (a runner that raises fails the whole
    group's futures as a safety net, so no submitter hangs)."""

    def __init__(
        self,
        runner: Callable[[List[BatchEntry]], None],
        wait_ms: Optional[float] = None,
        max_batch: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.runner = runner
        # None => consult the KnobRegistry per submit (live-tunable);
        # an explicit ctor value pins the window (tests, embedded uses)
        self._wait_ms_override: Optional[float] = (
            None if wait_ms is None else float(wait_ms)
        )
        self.max_batch = batch_max() if max_batch is None else int(max_batch)
        # injected clock => manual pump() (deterministic tests); the real
        # monotonic clock => lazy daemon worker wakes groups on deadline
        self._auto = clock is None
        self.clock = clock or time.monotonic
        self._cv = threads.Condition()
        self._groups: Dict[Hashable, _Group] = {}
        self._worker: Optional[Any] = None
        self._closed = False

    @property
    def wait_ms(self) -> float:
        """Coalescing window, read per decision (KnobRegistry-backed when
        not pinned at construction or by direct assignment)."""
        if self._wait_ms_override is not None:
            return self._wait_ms_override
        return batch_wait_ms()

    @wait_ms.setter
    def wait_ms(self, value: float) -> None:
        self._wait_ms_override = float(value)

    # -- submission ---------------------------------------------------------

    def submit(self, key: Hashable, payload: Any) -> Future:
        """Enqueue one query under its batch key; returns its Future.  Runs
        the group inline (in this caller's thread) when it fills to
        max_batch or when the wait window is 0."""
        entry = BatchEntry(payload)
        wait_ms = self.wait_ms  # one knob read per decision (coherent)
        if wait_ms <= 0 or self.max_batch <= 1:
            self._run([entry])
            return entry.future
        full: Optional[List[BatchEntry]] = None
        with self._cv:
            group = self._groups.get(key)
            if group is None:
                group = _Group(self.clock() + wait_ms / 1000.0)
                self._groups[key] = group
            group.entries.append(entry)
            if len(group.entries) >= self.max_batch:
                self._groups.pop(key, None)
                full = group.entries
            else:
                if self._auto and not self._closed:
                    self._ensure_worker()
                self._cv.notify_all()
        if full is not None:
            self._run(full)
        return entry.future

    # -- flushing -----------------------------------------------------------

    def pump(self, now: Optional[float] = None) -> int:
        """Flush every group whose wait window has expired as of ``now``
        (defaults to the clock).  Returns the number of groups run.  This
        is the deterministic test entry point and the worker's tick."""
        if now is None:
            now = self.clock()
        due: List[List[BatchEntry]] = []
        with self._cv:
            for key in [k for k, g in self._groups.items() if now >= g.deadline]:
                due.append(self._groups.pop(key).entries)
        for entries in due:
            self._run(entries)
        return len(due)

    def flush(self) -> int:
        """Flush every pending group regardless of deadline."""
        return self.pump(now=float("inf"))

    def pending(self) -> int:
        with self._cv:
            return sum(len(g.entries) for g in self._groups.values())

    def close(self) -> None:
        """Stop the worker and flush whatever is queued."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self.flush()

    # -- internals ----------------------------------------------------------

    def _run(self, entries: List[BatchEntry]) -> None:
        try:
            self.runner(entries)
        except BaseException as exc:  # pragma: no cover - runner safety net
            for entry in entries:
                if not entry.future.done():
                    entry.future.set_exception(exc)

    def _ensure_worker(self) -> None:
        # caller holds the condition lock
        if self._worker is None or not self._worker.is_alive():
            self._worker = threads.Thread(
                target=self._worker_main, name="query-batcher", daemon=True
            )
            self._worker.start()

    def _worker_main(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                if not self._groups:
                    self._cv.wait(timeout=0.5)
                    if not self._groups:
                        return  # idle — lazily restarted by the next submit
                    continue
                earliest = min(g.deadline for g in self._groups.values())
                delay = earliest - self.clock()
                if delay > 0:
                    self._cv.wait(timeout=delay)
                    continue
            self.pump()
