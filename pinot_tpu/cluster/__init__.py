"""Cluster layer: coordinator (controller), servers, broker routing.

Reference parity map (SURVEY.md §2.3, L6):
  coordinator.py - PinotHelixResourceManager (table CRUD :2045, addNewSegment
                   :3037 -> assignSegment :3056), segment assignment
                   strategies, TableRebalancer.rebalance (:201), periodic
                   tasks (RetentionManager, SegmentStatusChecker)
  server.py      - ServerInstance / HelixInstanceDataManager: per-server
                   segment ownership + local query execution
  broker.py      - BrokerRoutingManager (:33) routing tables, instance
                   selectors (balanced / replica-group), segment pruners
                   (partition, time), BaseSingleStageBrokerRequestHandler

Re-design: no Helix/ZooKeeper — a single-process coordinator owns the
metadata maps the reference keeps in ZK (ideal state / external view), and
"servers" are logical workers that pin their segment sets to device memory.
State transitions are direct method calls instead of Helix messages; the
CONTRACTS (replication, min-available-replicas rebalance, routing
consistency) match the reference.

Durability (PR 8): what the reference persists to ZooKeeper / the segment
deep store persists here through journal.py (fsync'd JSONL metadata WAL +
compacted snapshots) and deepstore.py (PinotFS-backed durable segment home
with CRC-verified download).  rebalance.py moves segments under query load
with load-before-drop ordering, and faults.py + utils/crashpoints.py form
the deterministic crash harness (scripted server crash/restart, named
kill-points inside every commit protocol).

Availability (r18): election.py makes the durable control plane HIGHLY
AVAILABLE — a lease file in meta_dir elects the leader, every journal
append carries its epoch (the fencing token the journal validates under
its lock), a hot standby tails the journal and promotes on lease expiry,
and brokers hold a CoordinatorHandle that rides NotLeaderError across the
failover while the data plane keeps serving the last versioned view.
"""
from pinot_tpu.cluster.admission import (
    AdmissionController,
    QueryCost,
    QueryKilledError,
    QueryWatchdog,
    ReservationError,
    ResourceBudget,
    ResourceGovernor,
    TooManyRequestsError,
    estimate_query_cost,
)
from pinot_tpu.cluster.coordinator import Coordinator
from pinot_tpu.cluster.server import ServerInstance
from pinot_tpu.cluster.broker import (
    Broker,
    HedgeController,
    NoReplicaAvailableError,
    ScatterGatherError,
    ServerHealth,
)
from pinot_tpu.cluster.deepstore import SegmentDeepStore
from pinot_tpu.cluster.election import (
    CoordinatorHandle,
    FencedEpochError,
    JournalFollower,
    LeaseManager,
    NotLeaderError,
)
from pinot_tpu.cluster.faults import FaultPlan, ServerFaultError
from pinot_tpu.cluster.journal import MetaJournal
from pinot_tpu.cluster.rebalance import TableRebalancer
from pinot_tpu.utils.crashpoints import InjectedCrash

__all__ = [
    "Coordinator",
    "ServerInstance",
    "Broker",
    "HedgeController",
    "ServerHealth",
    "FaultPlan",
    "ServerFaultError",
    "InjectedCrash",
    "CoordinatorHandle",
    "FencedEpochError",
    "JournalFollower",
    "LeaseManager",
    "NotLeaderError",
    "MetaJournal",
    "SegmentDeepStore",
    "TableRebalancer",
    "NoReplicaAvailableError",
    "ScatterGatherError",
    "AdmissionController",
    "QueryCost",
    "QueryKilledError",
    "QueryWatchdog",
    "ReservationError",
    "ResourceBudget",
    "ResourceGovernor",
    "TooManyRequestsError",
    "estimate_query_cost",
]
