"""SLO autopilot: the feedback loop that turns the serving tier's static
env knobs into a self-tuning system (ROADMAP item 5(b)).

Two halves:

``KnobRegistry`` — process-global, typed, clamped serving knobs.  Every
knob's INITIAL value and clamp bounds come from the same ``PINOT_TPU_*``
env var the consumer used to read at construction; the registry stores
only *overrides*, swapped in as one immutable dict, so

  * a knob nobody has written reads its env default at decision time —
    with the autopilot disabled the whole surface is bit-exact with the
    pre-registry behavior, and tests that monkeypatch env vars still work;
  * a controller write takes effect on the NEXT decision (next query,
    next refill, next launch) without rebuilding broker/engine/batcher;
  * ``view()`` returns one coherent snapshot — a query can never observe
    a mid-tick mix of old and new knob values (the model-checked
    contract: analysis/models.py ``KnobModel``);
  * setters clamp to the static env-derived ceilings — the controller
    can *never* exceed what the deployment configured.

``Autopilot`` — a sim-clock-friendly controller on a fixed tick
(injectable clock, utils/threads primitives so the deterministic
scheduler can drive it).  It reads the PerfLedger windows (admitted p99,
roofline %, plan-cache hit rate, QPS), hedge/brownout counters, and
ResourceBudget high-water marks, and moves AT MOST ONE knob per tick
along a fixed degradation ladder:

    shed hedges (budget pct, multiplicative decrease)
      -> widen the batch window (more coalescing per launch)
      -> shrink the macro-batch pipeline depth
      -> shrink the staging window
      -> cut the admission refill rate
      -> walk the r11 degradation ladder up (cooldown after every walk)

Policy is deliberately boring and provable: a hysteresis band around the
SLO (breach above ``slo_ms``, recovery only below ``recover_ratio *
slo_ms``, no moves in between), sustained-signal streaks before any
move, multiplicative-decrease on degrade / additive-increase on recover,
anti-windup (saturated knobs are skipped, never pushed), recovery
climbing back the SAME ladder in reverse, and a bounded knob-change
count per rolling window so the loop can never oscillate.
"""
from __future__ import annotations

import collections
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from pinot_tpu.utils import perf, threads
from pinot_tpu.utils.metrics import METRICS


def autopilot_enabled() -> bool:
    """PINOT_TPU_AUTOPILOT toggle; default off (pre-PR behavior)."""
    return os.environ.get("PINOT_TPU_AUTOPILOT", "0").lower() in ("1", "true", "yes")


# ---------------------------------------------------------------------------
# knob specs: env-derived initial value + clamp bounds per knob
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class KnobSpec:
    """One registry-managed serving knob.

    ``lo``/``hi`` are functions of the env-derived initial value — the
    static configuration *is* the ceiling; the controller moves inside
    it.  ``degrade`` names the direction the degradation ladder moves
    this knob ("down" shrinks toward ``lo``, "up" grows toward ``hi``).
    ``step`` is the additive-increase recovery step (0 = initial/8)."""

    name: str
    env: str
    default: float
    lo: Callable[[float], float]
    hi: Callable[[float], float]
    step: float = 1.0
    integer: bool = False
    degrade: str = "down"

    def initial(self) -> float:
        raw = os.environ.get(self.env)
        try:
            v = float(raw) if raw not in (None, "") else float(self.default)
        except ValueError:
            v = float(self.default)
        return float(int(v)) if self.integer else v

    def step_of(self, initial: float) -> float:
        return self.step if self.step > 0 else max(abs(initial) / 8.0, 1e-6)


SPECS: Tuple[KnobSpec, ...] = (
    # broker micro-batcher coalescing window (cluster/batcher.py)
    KnobSpec(
        "batch_wait_ms", "PINOT_TPU_BATCH_WAIT_MS", 2.0,
        lo=lambda i: 0.0, hi=lambda i: max(4.0 * i, i + 6.0),
        step=1.0, degrade="up",
    ),
    # macro-batch in-flight launch depth (parallel/engine.py)
    KnobSpec(
        "pipeline_depth", "PINOT_TPU_PIPELINE_DEPTH", 2,
        lo=lambda i: 1.0, hi=lambda i: max(i, 1.0),
        step=1.0, integer=True, degrade="down",
    ),
    # scatter staging window: segments resident at once (cluster/server.py)
    KnobSpec(
        "staging_depth", "PINOT_TPU_STAGING_DEPTH", 2,
        lo=lambda i: 1.0, hi=lambda i: max(i, 1.0),
        step=1.0, integer=True, degrade="down",
    ),
    # hedge launch budget as % of primaries (cluster/broker.py)
    KnobSpec(
        "hedge_budget_pct", "PINOT_TPU_HEDGE_BUDGET_PCT", 10.0,
        lo=lambda i: 0.0, hi=lambda i: max(i, 0.0),
        step=0.0, degrade="down",
    ),
    # hedge delay as a multiple of the peer p95 (cluster/broker.py)
    KnobSpec(
        "hedge_delay_mult", "PINOT_TPU_HEDGE_QUANTILE_MULT", 1.0,
        lo=lambda i: max(i, 1e-6), hi=lambda i: 4.0 * max(i, 1e-6),
        step=0.0, degrade="up",
    ),
    # admission token-bucket refill rate; env 0 = admission off -> inert
    KnobSpec(
        "admission_rate", "PINOT_TPU_ADMISSION_RATE", 0.0,
        lo=lambda i: 0.25 * i, hi=lambda i: i,
        step=0.0, degrade="down",
    ),
    # r11 degradation-ladder FLOOR (the occupancy signal can still push
    # the effective level higher; the controller can only raise the floor)
    KnobSpec(
        "degrade_level", "PINOT_TPU_DEGRADE_FLOOR", 0,
        lo=lambda i: 0.0, hi=lambda i: 3.0,
        step=1.0, integer=True, degrade="up",
    ),
)


# degrade order; recovery climbs back the same path in reverse
LADDER: Tuple[str, ...] = (
    "hedge_budget_pct",
    "batch_wait_ms",
    "pipeline_depth",
    "staging_depth",
    "admission_rate",
    "degrade_level",
)


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------


class KnobRegistry:
    """Clamped, typed, atomically-snapshotted serving knobs.

    Overrides live in ONE immutable dict swapped under ``_lock`` —
    ``set_many`` is a single swap, so readers (``get``/``view``) always
    see a coherent tick, never a mid-tick mix.  Unset knobs fall through
    to their env default, read at decision time."""

    def __init__(self, specs: Optional[Tuple[KnobSpec, ...]] = None):
        self._specs: Dict[str, KnobSpec] = {s.name: s for s in (specs or SPECS)}
        self._lock = threads.Lock()
        self._overrides: Dict[str, float] = {}
        self._splits: Dict[str, float] = {}

    # -- reads ----------------------------------------------------------
    def spec(self, name: str) -> KnobSpec:
        return self._specs[name]

    def names(self) -> List[str]:
        return list(self._specs)

    def initial(self, name: str) -> float:
        return self._specs[name].initial()

    def bounds(self, name: str) -> Tuple[float, float]:
        s = self._specs[name]
        init = s.initial()
        lo, hi = s.lo(init), s.hi(init)
        return (min(lo, hi), max(lo, hi))

    def get(self, name: str) -> float:
        with self._lock:
            ov = self._overrides  # the override dict is swapped, never mutated
        v = ov.get(name)
        return v if v is not None else self._specs[name].initial()

    def view(self) -> Dict[str, float]:
        """Coherent per-decision snapshot of every knob."""
        with self._lock:
            ov = self._overrides
        return {n: (ov[n] if n in ov else s.initial()) for n, s in self._specs.items()}

    # -- writes (clamped; the only mutation path — lint W026) -----------
    def _clamp(self, name: str, value: float) -> float:
        s = self._specs[name]
        lo, hi = self.bounds(name)
        v = min(hi, max(lo, float(value)))
        return float(int(round(v))) if s.integer else v

    def set(self, name: str, value: float, who: str = "manual") -> float:
        return self.set_many({name: value}, who=who)[name]

    def set_many(self, updates: Dict[str, float], who: str = "manual") -> Dict[str, float]:
        """Clamp and apply every update in ONE atomic swap (one tick =
        one swap); returns the values actually applied."""
        applied = {n: self._clamp(n, v) for n, v in updates.items()}
        with self._lock:
            merged = dict(self._overrides)
            merged.update(applied)
            self._overrides = merged
        for n, v in applied.items():
            METRICS.gauge(f"autopilot.knob.{n}").set(v)
        return applied

    # -- per-table residency budget splits ------------------------------
    def set_splits(self, splits: Dict[str, float], who: str = "autopilot") -> None:
        """Replace the per-table residency split weights (fractions of the
        HBM cache budget) in one swap; empty dict restores pure heat/LRU
        eviction (the pre-registry policy)."""
        clean = {t: max(0.0, float(f)) for t, f in splits.items()}
        with self._lock:
            self._splits = dict(clean)
        for t, f in clean.items():
            METRICS.gauge(f"autopilot.split.{t}").set(f)

    def splits(self) -> Dict[str, float]:
        with self._lock:
            s = self._splits  # swapped atomically, never mutated in place
        return dict(s)

    # -- observability ---------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            ov = self._overrides
            splits = dict(self._splits)
        out: Dict[str, Any] = {}
        for n, s in self._specs.items():
            lo, hi = self.bounds(n)
            v = ov[n] if n in ov else s.initial()
            METRICS.gauge(f"autopilot.knob.{n}").set(float(v))
            out[n] = {
                "value": v,
                "initial": s.initial(),
                "lo": lo,
                "hi": hi,
                "overridden": n in ov,
                "degrade": s.degrade,
            }
        return {"knobs": out, "splits": splits}

    def reset(self) -> None:
        with self._lock:
            self._overrides = {}
            self._splits = {}


_KNOBS_LOCK = threads.Lock()
# Constructed eagerly so the registry's lock comes from the ambient (real)
# thread provider: a lazy first touch inside a model-checker schedule would
# bind the lock to that scheduler and break replay determinism.
_REGISTRY: KnobRegistry = KnobRegistry()


def knobs() -> KnobRegistry:
    """The process-global registry every consumer consults per decision."""
    with _KNOBS_LOCK:
        return _REGISTRY


def reset_knobs() -> None:
    """Drop every override and split (tests; conftest autouse)."""
    global _REGISTRY
    with _KNOBS_LOCK:
        _REGISTRY = KnobRegistry()


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------


class Autopilot:
    """Fixed-tick feedback controller over the KnobRegistry.

    ``tick()`` is the whole control law and is directly drivable by
    tests/benches (fake clock, no thread); ``start()`` runs it on a
    daemon thread via utils/threads primitives.  One knob moves per
    tick, at most ``max_changes_per_window`` moves per rolling window of
    ticks, with a cooldown after every degradation-ladder walk."""

    def __init__(
        self,
        registry: Optional[KnobRegistry] = None,
        ledger: Optional[Any] = None,
        governor: Optional[Any] = None,
        clock: Optional[Callable[[], float]] = None,
        tick_s: Optional[float] = None,
        slo_ms: Optional[float] = None,
        history: int = 64,
    ):
        self.registry = registry if registry is not None else knobs()
        self.ledger = ledger if ledger is not None else perf.PERF_LEDGER
        self.governor = governor
        self.clock = clock if clock is not None else threads.monotonic
        self.tick_s = (
            float(os.environ.get("PINOT_TPU_AUTOPILOT_TICK_S", "1.0"))
            if tick_s is None
            else float(tick_s)
        )
        self.slo_ms = (
            float(os.environ.get("PINOT_TPU_SLO_MS", "250"))
            if slo_ms is None
            else float(slo_ms)
        )
        # hysteresis band: breach above slo_ms, recover below this ratio
        self.recover_ratio = 0.7
        self.breach_ticks = 2  # sustained-breach evidence before degrading
        self.recover_ticks = 3  # sustained-health evidence before recovering
        self.cooldown_ticks = 3  # after every degradation-ladder walk
        self.md_factor = 0.5  # multiplicative decrease
        self.change_window = 16  # ticks per oscillation-bound window
        self.max_changes_per_window = 4
        self._ladder = [n for n in LADDER if n in self.registry.names()]
        self._lock = threads.Lock()
        self._stop = threads.Event()
        self._thread: Optional[Any] = None
        self._tick_n = 0
        self._cooldown = 0
        self._breach_streak = 0
        self._healthy_streak = 0
        self._decisions: collections.deque = collections.deque(maxlen=history)
        self._change_ticks: collections.deque = collections.deque(maxlen=256)
        # per-instance counters: the METRICS twins are process-global and
        # survive controller restarts, so snapshot() must not report them
        self._knob_changes = 0
        self._ladder_walks = 0
        self._tables: Dict[str, Any] = {}

    # -- signal plane ----------------------------------------------------
    def _signals(self) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Read the feedback signal: PerfLedger windows, hedge/brownout
        counters, budget high-water marks.  Telemetry failures degrade to
        an idle signal — the controller holds rather than dies."""
        tables: Dict[str, Any] = {}
        worst_p99: Optional[float] = None
        qps_total = 0.0
        hit_rates: List[float] = []
        roof = 0.0
        try:
            snap = self.ledger.snapshot()
            for tname, t in snap.get("tables", {}).items():
                p99 = None
                for shape in t.get("shapes", {}).values():
                    lat = shape.get("latencyMs", {})
                    v = lat.get("p99", lat.get("max"))
                    if v is not None and (p99 is None or v > p99):
                        p99 = float(v)
                    hr = shape.get("planCacheHitRate")
                    if hr is not None:
                        hit_rates.append(float(hr))
                    rf = (shape.get("rooflinePct", {}) or {}).get("mean")
                    if rf:
                        roof = max(roof, float(rf))
                tqps = float(t.get("qps", 0.0))
                qps_total += tqps
                tables[tname] = {
                    "p99_ms": p99,
                    "qps": tqps,
                    "state": (
                        "breach" if p99 is not None and p99 > self.slo_ms else "ok"
                    ),
                }
                if p99 is not None and tqps > 0 and (worst_p99 is None or p99 > worst_p99):
                    worst_p99 = p99
        except Exception:  # noqa: BLE001 — telemetry must not kill the loop
            METRICS.counter("autopilot.signalErrors").inc()
        sig: Dict[str, Any] = {
            "p99_ms": worst_p99,
            "qps": round(qps_total, 3),
            "planCacheHitRate": (
                round(sum(hit_rates) / len(hit_rates), 3) if hit_rates else None
            ),
            "rooflinePct": round(roof, 3),
            "hedgesLaunched": METRICS.counter("broker.hedgesLaunched").value,
            "hedgesDenied": METRICS.counter("broker.hedgesDenied").value,
            "pressureLevel": METRICS.gauge("admission.pressureLevel").value,
        }
        if self.governor is not None:
            try:
                sig["hostPeakBytes"] = int(self.governor.host_budget.peak)
                sig["occupancy"] = round(self.governor._occupancy(), 4)
            except Exception:  # noqa: BLE001 — optional source, hold on failure
                METRICS.counter("autopilot.signalErrors").inc()
        return sig, tables

    # -- control law ------------------------------------------------------
    def tick(self) -> Dict[str, Any]:
        sig, tables = self._signals()
        now = self.clock()
        with self._lock:
            self._tick_n += 1
            n = self._tick_n
            self._tables = tables
            decision: Dict[str, Any] = {
                "tick": n,
                "clock": round(now, 4),
                "action": "hold",
                "signal": sig,
            }
            p99 = sig.get("p99_ms")
            if self.slo_ms <= 0:
                decision["action"] = "disabled"
            elif self._cooldown > 0:
                self._cooldown -= 1
                decision["action"] = "cooldown"
                decision["remaining"] = self._cooldown
            elif p99 is None:
                # no traffic in the window: hold, decay the evidence
                self._breach_streak = 0
                self._healthy_streak = 0
                decision["action"] = "idle"
            elif p99 > self.slo_ms:
                self._healthy_streak = 0
                self._breach_streak += 1
                if self._breach_streak >= self.breach_ticks:
                    self._move_locked(n, decision, degrade=True)
                else:
                    decision["action"] = "breach-pending"
            elif p99 <= self.recover_ratio * self.slo_ms:
                self._breach_streak = 0
                self._healthy_streak += 1
                if self._healthy_streak >= self.recover_ticks:
                    self._move_locked(n, decision, degrade=False)
                else:
                    decision["action"] = "recover-pending"
            else:
                # inside the hysteresis band: no knob change, evidence resets
                self._breach_streak = 0
                self._healthy_streak = 0
            self._decisions.append(decision)
        self._update_splits(tables)
        return decision

    def _move_locked(self, n: int, decision: Dict[str, Any], degrade: bool) -> None:
        move = self._degrade_move() if degrade else self._recover_move()
        if move is None:
            decision["action"] = "saturated" if degrade else "recovered"
            self._breach_streak = 0
            self._healthy_streak = 0
            return
        name, new = move
        # oscillation bound: at most max_changes_per_window knob changes
        # per rolling change_window ticks — asserted by tests and bench
        while self._change_ticks and self._change_ticks[0] <= n - self.change_window:
            self._change_ticks.popleft()
        if len(self._change_ticks) >= self.max_changes_per_window:
            decision["action"] = "capped"
            decision["knob"] = name
            METRICS.counter("autopilot.movesCapped").inc()
            return
        old = self.registry.get(name)
        applied = self.registry.set(name, new, who="autopilot")
        self._change_ticks.append(n)
        # _move_locked runs under self._lock (held by tick); W004's lexical
        # scope can't see a caller-held lock
        self._knob_changes += 1  # pinot-lint: disable=W004
        METRICS.counter("autopilot.knobChanges").inc()
        decision["action"] = "degrade" if degrade else "recover"
        decision["knob"] = name
        decision["from"] = old
        decision["to"] = applied
        if name == "degrade_level":
            self._ladder_walks += 1  # pinot-lint: disable=W004
            METRICS.counter("autopilot.ladderWalks").inc()
            self._cooldown = self.cooldown_ticks
        # a move consumes its evidence: the next one needs a fresh streak
        self._breach_streak = 0
        self._healthy_streak = 0

    def _degrade_move(self) -> Optional[Tuple[str, float]]:
        """First non-saturated knob in ladder order, moved one MD step in
        its degrade direction.  Saturated knobs are skipped (anti-windup:
        integrating further past the clamp would only delay recovery)."""
        reg = self.registry
        for name in self._ladder:
            s = reg.spec(name)
            init = reg.initial(name)
            if name == "admission_rate" and init <= 0:
                continue  # admission disabled by env: the knob is inert
            lo, hi = reg.bounds(name)
            cur = reg.get(name)
            step = s.step_of(init)
            if s.degrade == "down":
                if cur <= lo + 1e-9:
                    continue
                return name, max(lo, min(cur * self.md_factor, cur - step))
            if cur >= hi - 1e-9:
                continue
            return name, min(hi, max(cur * 2.0, cur + step))
        return None

    def _recover_move(self) -> Optional[Tuple[str, float]]:
        """Deepest displaced knob (reverse ladder order), one additive
        step back toward its env initial — recovery retraces the path."""
        reg = self.registry
        for name in reversed(self._ladder):
            s = reg.spec(name)
            init = reg.initial(name)
            cur = reg.get(name)
            if abs(cur - init) < 1e-9:
                continue
            step = s.step_of(init)
            if s.degrade == "down":
                return name, min(init, cur + step)
            return name, max(init, cur - step)
        return None

    def _update_splits(self, tables: Dict[str, Any]) -> None:
        """Resize per-table residency splits by measured traffic share —
        only when at least two tables carry load (a single-tenant process
        keeps the pre-registry pure heat/LRU eviction)."""
        active = {t: d["qps"] for t, d in tables.items() if d.get("qps", 0.0) > 0}
        if len(active) < 2:
            return
        total = sum(active.values())
        if total <= 0:
            return
        self.registry.set_splits({t: q / total for t, q in active.items()})

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop = threads.Event()
            self._thread = threads.Thread(
                target=self._run, name="autopilot", daemon=True
            )
            self._thread.start()

    # sensing backoff: a steady controller stretches its own cadence up to
    # 8x tick_s so a converged loop stops taxing the serving path it tunes.
    # "hold"/"idle" are steady by definition; "saturated" is too — breaching
    # with nothing left to move, re-sensing faster changes nothing until the
    # load eases.  Any evidence tick (breach/recover pending), move, or
    # cooldown snaps the cadence back to tick_s.
    _STEADY_ACTIONS = ("hold", "idle", "recovered", "saturated", "disabled")
    max_idle_backoff = 8

    @classmethod
    def _next_backoff(cls, backoff: int, action: str) -> int:
        if action in cls._STEADY_ACTIONS:
            return min(backoff * 2, cls.max_idle_backoff)
        return 1

    def _run(self) -> None:
        with self._lock:
            stop = self._stop
        backoff = 1
        while not stop.wait(timeout=self.tick_s * backoff):
            decision = self.tick()
            backoff = self._next_backoff(backoff, decision.get("action", ""))

    def stop(self) -> None:
        with self._lock:
            stop = self._stop
            t = self._thread
        stop.set()
        if t is not None:
            t.join(timeout=5.0)

    # -- observability ----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            decisions = list(self._decisions)
            tables = dict(self._tables)
            state = {
                "ticks": self._tick_n,
                "cooldown": self._cooldown,
                "breachStreak": self._breach_streak,
                "healthyStreak": self._healthy_streak,
                "running": self._thread is not None and self._thread.is_alive(),
                "knobChanges": self._knob_changes,
                "ladderWalks": self._ladder_walks,
            }
        reg = self.registry.snapshot()
        return {
            "enabled": True,
            "sloMs": self.slo_ms,
            "tickS": self.tick_s,
            **state,
            **reg,
            "tables": tables,
            "decisions": decisions,
            "changeBound": {
                "windowTicks": self.change_window,
                "maxChanges": self.max_changes_per_window,
            },
        }
