"""Broker: routing tables, instance selection, segment pruning, reduce.

Reference parity: BrokerRoutingManager (pinot-broker/.../routing/manager/
BrokerRoutingManager.java:33) building per-table segment->server maps from
the external view; instance selectors (BalancedInstanceSelector,
ReplicaGroupInstanceSelector); segment pruners (.../routing/segmentpruner/ —
SinglePartitionColumnSegmentPruner, TimeSegmentPruner); and the
scatter-gather + reduce of BaseSingleStageBrokerRequestHandler.handleRequest
(:342).

Re-design: scatter is a direct method call per server (the in-process data
plane; cross-host would ride the mesh collectives instead, SURVEY §2.6);
everything else — routing consistency, pruning, one-replica-per-segment
selection — matches the reference contracts.
"""
from __future__ import annotations

import itertools
import queue
import random
import statistics
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.cluster.admission import (
    QueryKilledError,
    ReservationError,
    ResourceGovernor,
    estimate_query_cost,
)
from pinot_tpu.query import reduce as reduce_mod
from pinot_tpu.query.ir import FilterNode, FilterOp, PredicateType, QueryContext
from pinot_tpu.query.result import ExecutionStats, ResultTable
from pinot_tpu.query.safety import Deadline, QueryTimeoutError
from pinot_tpu.utils import threads
from pinot_tpu.utils.hashing import partition_of
from pinot_tpu.utils.metrics import METRICS, Trace
from pinot_tpu.utils.slowlog import SlowQueryLog


class QuotaExceededError(RuntimeError):
    """Per-table QPS quota hit (the reference returns 429 with
    BrokerErrorCode QUERY_QUOTA_EXCEEDED)."""


class NoReplicaAvailableError(RuntimeError):
    """A segment has no live replica left to route to (after exclusions)."""


class ScatterGatherError(RuntimeError):
    """A scatter call failed on every tried replica and the query did not
    opt into allowPartialResults; carries the per-server exception list."""

    def __init__(self, message: str, exceptions: Optional[List[Dict]] = None):
        super().__init__(message)
        self.exceptions = list(exceptions or [])


class QueryQuotaManager:
    """Per-table query rate limiting (HelixExternalViewBasedQueryQuotaManager,
    pinot-broker/.../broker/queryquota/).  Token bucket per table against
    TableConfig quota.maxQueriesPerSecond — refill rate q, burst capacity
    max(1, q), so fractional quotas (q=0.5 -> one query per 2s) throttle
    correctly.  The reference divides the table quota across online
    brokers — single broker here, so the full quota applies (documented)."""

    def __init__(self) -> None:
        # table -> [tokens, last_refill_monotonic]
        self._buckets: Dict[str, List[float]] = {}
        self.clock = time.monotonic  # injectable for deterministic tests
        # the refill/charge sequence is a read-modify-write: concurrent REST
        # handler threads would over-admit past the bucket (ADVICE r5 race)
        self._lock = threading.Lock()

    def check(self, table: str, max_qps: float, now: Optional[float] = None) -> None:
        if max_qps <= 0:
            return
        t = self.clock() if now is None else now
        cap = max(1.0, float(max_qps))
        with self._lock:
            b = self._buckets.get(table)
            if b is None:
                b = self._buckets[table] = [cap, t]
            tokens = min(cap, b[0] + max_qps * (t - b[1]))
            b[1] = t
            if tokens < 1.0:
                b[0] = tokens
                raise QuotaExceededError(
                    f"table {table!r} exceeded maxQueriesPerSecond={max_qps:g}"
                )
            b[0] = tokens - 1.0


class AdaptiveServerStats:
    """Latency-biased replica scoring (pinot-broker/.../routing/
    adaptiveserverselector/ — NumInFlightReqSelector + LatencySelector
    hybrid): servers rank by EWMA latency scaled by (1 + in-flight), so
    slow or busy replicas shed load to their peers."""

    ALPHA = 0.3

    def __init__(self) -> None:
        self.ewma_ms: Dict[str, float] = {}
        self.in_flight: Dict[str, int] = {}
        # begin/end race from concurrent scatter threads: unlocked, two
        # begins could both read in_flight=0 and a decay update could be
        # lost entirely (ADVICE r5 race class)
        self._lock = threading.Lock()

    def begin(self, server: str) -> None:
        with self._lock:
            self.in_flight[server] = self.in_flight.get(server, 0) + 1

    def end(self, server: str, latency_ms: float) -> None:
        with self._lock:
            self.in_flight[server] = max(0, self.in_flight.get(server, 1) - 1)
            prev = self.ewma_ms.get(server)
            self.ewma_ms[server] = (
                latency_ms if prev is None else prev + self.ALPHA * (latency_ms - prev)
            )

    def score(self, server: str) -> float:
        # unseen servers score best (explore), matching the reference's
        # default-to-fallback behavior for servers without stats; snapshot
        # under the lock so a concurrent end() can't tear lat/in_flight
        with self._lock:
            lat = self.ewma_ms.get(server, 0.0)
            in_flight = self.in_flight.get(server, 0)
        return lat * (1.0 + in_flight)

    def punish(self, server: str, factor: float = 2.0, floor_ms: float = 50.0) -> None:
        """Failure feedback from the circuit-breaker path: a failed scatter
        call counts as a slow response, so the adaptive selector sheds
        traffic from flaky replicas BEFORE they trip quarantine."""
        with self._lock:
            prev = self.ewma_ms.get(server, 0.0)
            self.ewma_ms[server] = max(prev * factor, floor_ms)


class ServerHealth:
    """Consecutive-failure circuit breaker over scatter targets
    (the AdaptiveServerSelector "unhealthy server" shedding +
    SERVER_NOT_RESPONDING handling collapsed into one explicit breaker).

    States per server: CLOSED (healthy) -> OPEN after `failure_threshold`
    consecutive scatter failures (quarantined: receives no routes while a
    healthy replica exists) -> HALF_OPEN once `cooldown_s` elapses on the
    monotonic clock (at most ONE in-flight probe query is allowed through;
    success closes the breaker, failure re-opens it with a fresh cooldown).

    Quarantine is advisory, never availability-destroying: when every
    replica of a segment is quarantined the router still uses them (serving
    a maybe-flaky replica beats failing the query outright).

    Orthogonal to the breaker, a BROWNOUT state tracks gray failure (slow
    but alive — the breaker never sees an error): each server keeps a
    rolling window of observed scatter latencies, and a server whose window
    median is `brownout_factor`x the median of its peers' medians enters
    brownout.  Browned servers stay available() — the router only WEIGHTS
    them away (prefers non-browned candidates), so availability never
    drops.  Recovery mirrors the half-open probe: once `brownout_cooldown_s`
    elapses the deprioritization lifts, probe traffic flows, and the next
    latency evaluation either clears the brownout or re-stamps it."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 30.0):
        import os

        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self.clock = time.monotonic  # injectable for deterministic tests
        self._lock = threads.Lock()
        self._consecutive: Dict[str, int] = {}
        self._opened_at: Dict[str, float] = {}  # server -> quarantine start
        self._probing: Set[str] = set()  # half-open probes in flight
        # -- gray-failure (brownout) detection --------------------------------
        self.brownout_factor = float(os.environ.get("PINOT_TPU_BROWNOUT_FACTOR", "3.0"))
        self.brownout_min_samples = int(os.environ.get("PINOT_TPU_BROWNOUT_MIN_SAMPLES", "8"))
        self.brownout_cooldown_s = float(
            os.environ.get("PINOT_TPU_BROWNOUT_COOLDOWN_S", str(cooldown_s))
        )
        # absolute floor: sub-floor medians never brown a server, so noise on
        # microsecond-scale test queries can't trigger spurious routing shifts
        self.brownout_min_ms = float(os.environ.get("PINOT_TPU_BROWNOUT_MIN_MS", "2.0"))
        self._latency: Dict[str, "deque"] = {}  # rolling per-server windows
        self._browned: Dict[str, float] = {}  # server -> brownout start

    def record_failure(self, server: str) -> None:
        with self._lock:
            n = self._consecutive.get(server, 0) + 1
            self._consecutive[server] = n
            was_open = server in self._opened_at
            self._probing.discard(server)
            if n >= self.failure_threshold or was_open:
                # threshold hit, or a half-open probe failed: (re-)quarantine
                self._opened_at[server] = self.clock()
                if not was_open:
                    METRICS.counter("broker.serversQuarantined").inc()
            self._publish_gauges_locked(server)

    def record_success(self, server: str) -> None:
        with self._lock:
            self._consecutive[server] = 0
            if self._opened_at.pop(server, None) is not None:
                METRICS.counter("broker.serversRecovered").inc()
            self._probing.discard(server)
            self._publish_gauges_locked(server)

    def _publish_gauges_locked(self, server: str) -> None:
        """Breaker-state gauges (caller holds self._lock): total open
        breakers plus a per-server 0/1 flag for alerting on one replica."""
        METRICS.gauge("broker.openBreakers").set(len(self._opened_at))
        METRICS.gauge(f"broker.breakerOpen.{server}").set(
            1.0 if server in self._opened_at else 0.0
        )
        METRICS.gauge("broker.brownouts").set(len(self._browned))
        METRICS.gauge(f"broker.brownout.{server}").set(
            1.0 if server in self._browned else 0.0
        )

    def note_latency(self, server: str, latency_ms: float) -> Optional[str]:
        """Feed one observed scatter latency and re-evaluate brownout for the
        server.  Returns "enter"/"exit" on a brownout transition, else None.
        This is the ONLY path that moves brownout state — record_failure /
        record_success never touch it, keeping breaker and brownout fully
        independent (a browned server can trip its breaker and vice versa)."""
        with self._lock:
            win = self._latency.get(server)
            if win is None:
                win = self._latency[server] = deque(maxlen=32)
            win.append(float(latency_ms))
            return self._evaluate_brownout_locked(server)

    def _evaluate_brownout_locked(self, server: str) -> Optional[str]:
        win = self._latency.get(server)
        if win is None or len(win) < self.brownout_min_samples:
            return None
        peer_medians = [
            statistics.median(w)
            for s, w in self._latency.items()
            if s != server and len(w) >= self.brownout_min_samples
        ]
        if not peer_medians:
            return None  # outlier-vs-peers needs at least one mature peer
        own = statistics.median(win)
        peers = statistics.median(peer_medians)
        browned_at = self._browned.get(server)
        is_outlier = own >= self.brownout_min_ms and own > self.brownout_factor * peers
        now = self.clock()
        if is_outlier:
            if browned_at is None:
                self._browned[server] = now
                METRICS.counter("broker.serversBrownedOut").inc()
                self._publish_gauges_locked(server)
                return "enter"
            if now - browned_at >= self.brownout_cooldown_s:
                # the half-open-style probe still looks slow: re-stamp the
                # cooldown, exactly like a failed breaker probe re-opens
                self._browned[server] = now
            return None
        if browned_at is not None and now - browned_at >= self.brownout_cooldown_s:
            # probe traffic after the cooldown came back at peer speed
            del self._browned[server]
            METRICS.counter("broker.brownoutRecoveries").inc()
            self._publish_gauges_locked(server)
            return "exit"
        return None

    def in_brownout(self, server: str) -> bool:
        with self._lock:
            return server in self._browned

    def brownout_deprioritized(self, server: str) -> bool:
        """Should the router weight this server away right now?  True while
        browned and inside the cooldown; after the cooldown the server takes
        normal traffic again (the probe window) until note_latency clears or
        re-stamps the brownout."""
        with self._lock:
            t = self._browned.get(server)
            return t is not None and self.clock() - t < self.brownout_cooldown_s

    def latency_window(self, server: str) -> List[float]:
        with self._lock:
            return list(self._latency.get(server, ()))

    def state(self, server: str) -> str:
        with self._lock:
            t = self._opened_at.get(server)
            if t is None:
                return "brownout" if server in self._browned else "closed"
            return "half_open" if self.clock() - t >= self.cooldown_s else "open"

    def available(self, server: str) -> bool:
        """Routable right now?  CLOSED: yes.  OPEN: no.  HALF_OPEN: yes,
        unless another probe is already in flight."""
        with self._lock:
            t = self._opened_at.get(server)
            if t is None:
                return True
            if self.clock() - t < self.cooldown_s:
                return False
            return server not in self._probing

    def begin_probe(self, server: str) -> None:
        """Mark a routed call as the half-open probe (single-flight)."""
        with self._lock:
            if server in self._opened_at:
                self._probing.add(server)

    def consecutive_failures(self, server: str) -> int:
        with self._lock:
            return self._consecutive.get(server, 0)

    def reset(self, server: str) -> None:
        """Fresh slate on a coordinator live-set recovery (mark_up): the
        re-registered server is a new Helix session, not the flaky old one."""
        with self._lock:
            self._consecutive.pop(server, None)
            self._opened_at.pop(server, None)
            self._probing.discard(server)
            self._browned.pop(server, None)
            self._latency.pop(server, None)
            self._publish_gauges_locked(server)


def _p95(values) -> float:
    xs = sorted(values)
    return xs[min(len(xs) - 1, int(round(0.95 * (len(xs) - 1))))]


class HedgeController:
    """Policy + bookkeeping for hedged scatter calls (the tail-tolerance
    half of "The Tail at Scale"): per-(table, server) rolling latency
    windows derive the hedge delay (a multiple of the PEER replicas' p95 —
    a chronically slow primary must not inflate its own trigger), and a
    launch budget caps hedges at `budget_pct`% of primary launches so
    hedging can never amplify an overload.  Hedging is opt-in: the
    PINOT_TPU_HEDGE env toggle or the per-query `hedge` option.

    Env knobs: PINOT_TPU_HEDGE (enable), PINOT_TPU_HEDGE_DELAY_MS (flat
    delay override, skips the quantile derivation), PINOT_TPU_HEDGE_BUDGET_PCT
    (default 10), PINOT_TPU_HEDGE_MIN_SAMPLES (default 8),
    PINOT_TPU_HEDGE_QUANTILE_MULT (default 1.0), PINOT_TPU_HEDGE_MIN_DELAY_MS
    (default 1.0).  Query options `hedge`, `hedgeDelayMs`, `hedgeBudgetPct`
    override per query."""

    WINDOW = 64

    def __init__(self) -> None:
        import os

        env = os.environ
        self.enabled_default = env.get("PINOT_TPU_HEDGE", "0").lower() in ("1", "true", "yes")
        d = env.get("PINOT_TPU_HEDGE_DELAY_MS")
        self.env_delay_ms: Optional[float] = float(d) if d else None
        # budget_pct / quantile_mult read the autopilot KnobRegistry per
        # decision (env vars are the registry's initial values); a direct
        # assignment (tests, bench legs) pins the value via the override
        self._budget_pct_override: Optional[float] = None
        self._quantile_mult_override: Optional[float] = None
        self.min_samples = int(env.get("PINOT_TPU_HEDGE_MIN_SAMPLES", "8"))
        self.min_delay_ms = float(env.get("PINOT_TPU_HEDGE_MIN_DELAY_MS", "1.0"))
        self._lock = threading.Lock()
        self._windows: Dict[Tuple[str, str], deque] = {}
        self._primaries = 0
        self._hedges = 0

    @property
    def budget_pct(self) -> float:
        if self._budget_pct_override is not None:
            return self._budget_pct_override
        from pinot_tpu.cluster import autopilot

        return float(autopilot.knobs().get("hedge_budget_pct"))

    @budget_pct.setter
    def budget_pct(self, value: float) -> None:
        self._budget_pct_override = float(value)

    @property
    def quantile_mult(self) -> float:
        if self._quantile_mult_override is not None:
            return self._quantile_mult_override
        from pinot_tpu.cluster import autopilot

        return float(autopilot.knobs().get("hedge_delay_mult"))

    @quantile_mult.setter
    def quantile_mult(self, value: float) -> None:
        self._quantile_mult_override = float(value)

    def enabled(self, opts: Optional[Dict] = None) -> bool:
        if opts is not None and "hedge" in opts:
            return str(opts.get("hedge", "")).lower() in ("1", "true", "yes")
        return self.enabled_default

    def observe(self, table: str, server: str, latency_ms: float) -> None:
        with self._lock:
            key = (table, server)
            win = self._windows.get(key)
            if win is None:
                win = self._windows[key] = deque(maxlen=self.WINDOW)
            win.append(float(latency_ms))

    def delay_ms(self, table: str, primary: str, opts: Optional[Dict] = None) -> Optional[float]:
        """Hedge trigger delay for a call routed to `primary`, or None when
        there is not yet enough signal to hedge safely (cold start)."""
        if opts is not None and opts.get("hedgeDelayMs") is not None:
            return float(opts["hedgeDelayMs"])
        if self.env_delay_ms is not None:
            return self.env_delay_ms
        with self._lock:
            peer_p95s = [
                _p95(win)
                for (t, s), win in self._windows.items()
                if t == table and s != primary and len(win) >= self.min_samples
            ]
        if not peer_p95s:
            return None
        return max(self.min_delay_ms, self.quantile_mult * statistics.median(peer_p95s))

    def note_primary(self) -> None:
        with self._lock:
            self._primaries += 1

    def try_fire(self, opts: Optional[Dict] = None) -> bool:
        """Claim one hedge launch against the budget; False when the next
        hedge would push the hedge:primary ratio past budget_pct%."""
        pct = self.budget_pct
        if opts is not None and opts.get("hedgeBudgetPct") is not None:
            pct = float(opts["hedgeBudgetPct"])
        with self._lock:
            if (self._hedges + 1) > pct / 100.0 * self._primaries:
                return False
            self._hedges += 1
            return True

    def unfire(self) -> None:
        """Return a claimed launch (admission refused the charge)."""
        with self._lock:
            self._hedges = max(0, self._hedges - 1)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "primaries": self._primaries,
                "hedges": self._hedges,
                "budgetPct": self.budget_pct,
                "windows": len(self._windows),
            }


class _BatchMember:
    """One pre-admitted query riding the micro-batcher: the submit-time
    bracket (query id, trace root, deadline, governor grant + kill probe,
    cache key) plus the plan/prune products filled in by _plan_member."""

    __slots__ = (
        "ctx", "sql", "fp", "sfp", "qid", "trace", "deadline", "t0",
        "grant", "cancel", "ckey", "offline_ctx", "realtime_ctx",
        "seg_names", "pruned",
    )

    def __init__(self, ctx, sql, fp, sfp, qid, trace, deadline, t0, grant, cancel, ckey):
        self.ctx = ctx
        self.sql = sql
        self.fp = fp
        self.sfp = sfp
        self.qid = qid
        self.trace = trace
        self.deadline = deadline
        self.t0 = t0
        self.grant = grant
        self.cancel = cancel
        self.ckey = ckey
        self.offline_ctx = ctx
        self.realtime_ctx = ctx
        self.seg_names: List[str] = []
        self.pruned = 0


def _has_subquery(node: Optional[FilterNode]) -> bool:
    """IN (SELECT ...) anywhere in a filter tree — such queries keep the
    synchronous path (their subqueries recurse through execute())."""
    if node is None:
        return False
    from pinot_tpu.query.ir import Subquery

    if node.op is FilterOp.PRED:
        p = node.predicate
        return bool(p is not None and p.values and isinstance(p.values[0], Subquery))
    return any(_has_subquery(c) for c in (node.children or ()))


class Broker:
    def __init__(self, coordinator, selector: str = "balanced"):
        # coordinator HA (r18): the broker never holds a raw Coordinator —
        # everything routes through a CoordinatorHandle that re-resolves
        # leadership on NotLeaderError and keeps data-plane reads serving
        # off the last versioned routing view during a failover.  wrap() is
        # idempotent, so callers may pass a Coordinator OR a handle over a
        # leader + standbys.
        from pinot_tpu.cluster.election import CoordinatorHandle

        self.coordinator = CoordinatorHandle.wrap(coordinator)
        self.selector = selector  # "balanced" | "replicagroup" | "adaptive"
        self._rr = 0  # round-robin cursor
        self._rr_lock = threading.Lock()  # cursor bump is an RMW across handler threads
        # mesh-replica batch routing: whole same-fingerprint batches land on
        # one replica row each (replica group ≅ mesh replica row), rotated
        # per BATCH so concurrent batches spread across rows while every
        # member of a batch shares its row's compiled kernel + staged copy
        self._batch_rr = 0
        self.quota = QueryQuotaManager()
        self.server_stats = AdaptiveServerStats()
        self.health = ServerHealth()
        # failover backoff: injectable sleep + seeded jitter so fault tests
        # are deterministic and never wall-clock sensitive
        self.retry_rng = random.Random(0x5CA77E12)
        self._sleep = time.sleep
        # tail tolerance: hedged-scatter policy + live loser threads (each
        # loser is cooperatively cancelled via the cancel-probe path and
        # tracked here until it unwinds — hedge_drain() proves no leaks)
        self.hedge = HedgeController()
        self._hedge_threads: Set[threading.Thread] = set()
        self._hedge_lock = threading.Lock()
        # query-id mint: itertools.count is atomic under the GIL, so handler
        # threads never need a lock for the sequence (W004-clean by design)
        self._qid_seq = itertools.count(1)
        self._broker_id = f"{random.getrandbits(32):08x}"
        # recent-query ring buffer behind GET /debug/queries + cli slow-queries
        self.slow_queries = SlowQueryLog()
        # broker result cache: bytes-bounded LRU + TTL, keyed on the resolved
        # query fingerprint + a table version token (segment set + realtime
        # doc count), so segment churn or realtime appends miss naturally.
        # Serving from it is opt-in: the useResultCache query option or the
        # PINOT_TPU_RESULT_CACHE env toggle (off by default — repeated
        # execution semantics stay untouched unless asked for).
        import os

        from pinot_tpu.utils.cache import LruCache

        # resource governor (cluster/admission.py): token-bucket admission,
        # host-memory ledger, runaway watchdog, degradation controller.
        # The result cache charges the SAME host ledger the governor reserves
        # query working sets from, so cached bytes + in-flight queries can
        # never jointly overcommit host memory (r11).
        self.governor: Optional[ResourceGovernor] = ResourceGovernor()
        self.result_cache = LruCache(
            max_bytes=max(1, int(os.environ.get("PINOT_TPU_RESULT_CACHE_BYTES", str(64 << 20)))),
            ttl_s=float(os.environ.get("PINOT_TPU_RESULT_CACHE_TTL_S", "60")),
            name="broker.resultCache",
            budget=self.governor.host_budget,
        )
        # the SSE plan cache (servers compile through it) charges the same
        # ledger — idempotent for the shared process budget
        from pinot_tpu.query.planner import attach_plan_cache_budget

        attach_plan_cache_budget(self.governor.host_budget)
        # cross-query micro-batcher (cluster/batcher.py): built lazily on the
        # first submit() so brokers that never use the async tier never start
        # its worker.  Tests inject a fake clock via batch_clock BEFORE the
        # first submit and drive flushes deterministically with pump().
        self.batch_clock = None
        self._query_batcher = None
        self._batcher_lock = threading.Lock()
        # SLO autopilot (cluster/autopilot.py): the feedback controller that
        # tunes the KnobRegistry the batcher/hedge/admission/engine/residency
        # paths read per decision.  Off by default — with PINOT_TPU_AUTOPILOT
        # unset no controller thread exists, no knob override is ever written,
        # and every consumer reads its env default: pre-autopilot behavior
        # bit-exactly.  attach_autopilot() wires one explicitly (benches,
        # tests drive tick() by hand with a fake clock).
        from pinot_tpu.cluster import autopilot as autopilot_mod

        self.autopilot: Optional[autopilot_mod.Autopilot] = None
        if autopilot_mod.autopilot_enabled():
            self.attach_autopilot(start=True)
        # subscribe via the handle so the subscription is RECORDED and
        # re-registered on every newly adopted leader (breaker heal keeps
        # working across a failover)
        self.coordinator.on_live_change(self._on_live_change)

    @staticmethod
    def _result_cache_enabled(ctx: QueryContext) -> bool:
        import os

        opt = ctx.options.get("useResultCache")
        if opt is not None:
            return str(opt).lower() in ("1", "true", "yes")
        return os.environ.get("PINOT_TPU_RESULT_CACHE", "0").lower() in ("1", "true", "yes")

    def _table_version(self, table: str) -> Tuple:
        """Version token invalidating cached results on table churn: the
        offline segment set plus the realtime view's (segments, docs)."""
        meta = self.coordinator.tables.get(table)
        ideal = tuple(sorted(meta.ideal)) if meta is not None else ()
        rt = self.coordinator.realtime.get(table)
        rtv: Tuple = ()
        if rt is not None:
            segs = list(rt.query_segments())
            rtv = (len(segs), sum(s.num_docs for s in segs))
        return (ideal, rtv)

    def invalidate_results(self, table: str) -> int:
        """Explicitly drop every cached result for one table (segment
        reload / config change hook)."""
        return self.result_cache.invalidate_where(lambda k: k[0] == table)

    def _on_live_change(self, name: str, up: bool) -> None:
        """Coordinator live-set transition: a recovered server gets a fresh
        breaker (a new Helix session is not the old flaky process)."""
        if up:
            self.health.reset(name)

    def election_snapshot(self) -> Dict:
        """Leadership view for GET /debug/election: current leader plus
        per-candidate lease/epoch/role state."""
        return self.coordinator.election_snapshot()

    def attach_autopilot(self, controller=None, start: bool = False):
        """Wire an SLO autopilot to this broker (replacing any previous
        one).  Default construction feeds it the process PerfLedger and this
        broker's governor; `start` launches the fixed-tick thread."""
        from pinot_tpu.cluster import autopilot as autopilot_mod

        old = self.autopilot
        if old is not None:
            old.stop()
        if controller is None:
            controller = autopilot_mod.Autopilot(governor=self.governor)
        self.autopilot = controller
        if start:
            controller.start()
        return controller

    def autopilot_snapshot(self) -> Dict:
        """Knob values vs clamp bounds plus controller state for
        GET /debug/autopilot + `cli autopilot` — available with the
        controller detached too (registry-only view)."""
        from pinot_tpu.cluster import autopilot as autopilot_mod

        ap = self.autopilot
        if ap is not None:
            return ap.snapshot()
        return {"enabled": False, **autopilot_mod.knobs().snapshot()}

    # -- routing table (built per query from the external view) -----------
    def _route(
        self,
        table: str,
        seg_names: List[str],
        exclude: frozenset = frozenset(),
        partial_ok: bool = False,
        prefer_group: Optional[int] = None,
    ):
        """segment list -> {server: [segments]} picking ONE live replica per
        segment (InstanceSelector contract).

        `exclude`: servers that already failed this query (failover
        re-selection never retries them).  Quarantined servers (ServerHealth
        OPEN) are skipped while a healthy replica exists; when a segment's
        every replica is quarantined, availability wins and they serve.
        With partial_ok, returns (assign, unroutable_segments) instead of
        raising on a replica-less segment.  `prefer_group` (replicagroup
        selector only) starts the group rotation at that replica group —
        the batched scatter path uses it to pin a whole batch to one mesh
        replica row; a dead/partial preferred group still falls through the
        rotation, so it's a preference, never an availability constraint."""
        view = self.coordinator.external_view(table)
        healthy = {
            s for s in self.coordinator.live if s not in exclude and self.health.available(s)
        }
        usable = {s for s in self.coordinator.live if s not in exclude}
        with self._rr_lock:
            self._rr += 1
            rr = self._rr  # routing decisions below use this stable local
        if self.selector == "replicagroup":
            # strict replica-group: pick ONE group serving ALL segments
            groups: Dict[int, Set[str]] = {}
            for s in healthy:
                groups.setdefault(self.coordinator.replica_group[s], set()).add(s)
            order = sorted(groups)
            for gi in range(len(order)):
                if prefer_group is not None:
                    g = order[(prefer_group + gi) % len(order)]
                else:
                    g = order[(rr + gi) % len(order)]
                members = groups[g]
                assign: Dict[str, List[str]] = {}
                ok = True
                for seg in seg_names:
                    srv = sorted(view.get(seg, ()) & members)
                    if not srv:
                        ok = False
                        break
                    assign.setdefault(srv[0], []).append(seg)
                if ok:
                    return (assign, []) if partial_ok else assign
            # no single group covers everything: fall through to balanced
        assign = {}
        unroutable: List[str] = []
        for i, seg in enumerate(seg_names):
            replicas = view.get(seg, set())
            candidates = sorted(replicas & healthy) or sorted(replicas & usable)
            if not candidates:
                if partial_ok:
                    unroutable.append(seg)
                    continue
                raise NoReplicaAvailableError(f"segment {table}/{seg} has no live replica")
            # gray-failure weighting: prefer non-browned replicas, but a
            # fully-browned candidate set still serves (availability wins,
            # exactly like breaker quarantine above)
            bright = [c for c in candidates if not self.health.brownout_deprioritized(c)]
            if bright:
                candidates = bright
            if self.selector == "adaptive":
                # latency-biased: best (lowest) score wins; round-robin
                # breaks exact ties so cold starts still spread
                srv = min(
                    candidates,
                    key=lambda s, i=i: (self.server_stats.score(s), (rr + i + candidates.index(s)) % len(candidates)),
                )
            else:
                srv = candidates[(rr + i) % len(candidates)]
            assign.setdefault(srv, []).append(seg)
        return (assign, unroutable) if partial_ok else assign

    # -- segment pruners ---------------------------------------------------
    def _prune(self, ctx: QueryContext, table: str) -> Tuple[List[str], int]:
        """Partition + time pruning on broker-side segment metadata."""
        meta = self.coordinator.tables[table]
        names = list(meta.ideal)
        pruned = 0
        eq_values = _eq_values_by_column(ctx.filter)
        cfg = meta.config
        out = []
        for seg in names:
            sm = meta.segment_meta.get(seg, {})
            # partition pruner (SinglePartitionColumnSegmentPruner)
            part = sm.get("partition")
            if part is not None and part[0] in eq_values:
                col, pid, n = part
                if all(partition_of(v, n) != pid for v in eq_values[col]):
                    pruned += 1
                    continue
            # time pruner (TimeSegmentPruner)
            tc = cfg.segments.time_column
            tr = sm.get("timeRange")
            if tc and tr is not None and tr[0] is not None:
                lo, hi = _range_for_column(ctx.filter, tc)
                if (hi is not None and tr[0] is not None and tr[0] > hi) or (
                    lo is not None and tr[1] is not None and tr[1] < lo
                ):
                    pruned += 1
                    continue
            out.append(seg)
        return out, pruned

    # -- request handling --------------------------------------------------
    def query(self, sql: str) -> ResultTable:
        from pinot_tpu.sql.parser import parse_query

        ctx = parse_query(sql)
        if ctx.options.get("__explain__"):
            return self.execute(ctx)  # plan-only: not a served query
        fp = ctx.fingerprint()
        sfp = ctx.shape_fingerprint()
        try:
            out = self.execute(ctx)
        except Exception as e:
            self.slow_queries.record(
                sql, fp, None, error=f"{type(e).__name__}: {e}", shape_fingerprint=sfp
            )
            raise
        self.slow_queries.record(sql, fp, out, shape_fingerprint=sfp)
        return out

    def execute(self, ctx: QueryContext, _charged: frozenset = frozenset()) -> ResultTable:
        from pinot_tpu.query.engine import apply_set_ops, resolve_subqueries
        from pinot_tpu.spi.env import apply_env_defaults

        apply_env_defaults(ctx.options)
        if ctx.options.get("__explain__"):
            return self._explain(ctx)
        if ctx.options.get("__analyze__"):
            return self._explain_analyze(ctx)
        # quota charges ONCE per client request PER TABLE — set-op operands
        # and subqueries recurse with their outer tables pre-paid, but a
        # different table inside the request still pays its own quota
        # (review-caught: inner tables must not bypass their limits)
        if ctx.table not in _charged and ctx.table in self.coordinator.tables:
            self.quota.check(
                ctx.table, self.coordinator.tables[ctx.table].config.max_queries_per_second
            )
        charged = _charged | {ctx.table}
        _sub = lambda c: self.execute(c, _charged=charged)
        resolve_subqueries(ctx, _sub)
        if ctx.set_ops:
            return apply_set_ops(ctx, _sub)
        t0 = time.perf_counter()
        deadline = Deadline.from_ctx(ctx)
        if ctx.joins:
            raise NotImplementedError("broker routes single-table queries; joins ride the MSE engine")
        table = ctx.table
        if table not in self.coordinator.tables:
            raise KeyError(f"table {table!r} not found")
        # root span: the broker mints the query id; every server subtree
        # grafts under this one tree (RequestContext analog)
        qid = f"{self._broker_id}_{next(self._qid_seq)}"
        trace = Trace(bool(ctx.options.get("trace", False)), query_id=qid)
        METRICS.counter("broker.queries").inc()
        # admission bracket: root client requests only (subquery/set-op
        # recursion rides the parent's grant).  Sheds (429) and capacity
        # rejections (503) raise HERE, after the qid mint, so every
        # structured rejection carries the query id; the grant's host
        # reservation + watchdog registration release in the finally on
        # every exit path (success, timeout, kill, server fault).
        grant = None
        cancel = None
        gov = self.governor
        if gov is not None and not _charged:
            cost = estimate_query_cost(ctx, self.coordinator.tables[table].segment_meta.values())
            grant = gov.admit(qid, ctx, cost, deadline)
            cancel = gov.cancel_probe(qid)
        try:
            return self._serve(ctx, table, qid, trace, deadline, t0, cancel)
        finally:
            if grant is not None:
                grant.close()

    def _serve(
        self,
        ctx: QueryContext,
        table: str,
        qid: str,
        trace: Trace,
        deadline: Deadline,
        t0: float,
        cancel=None,
    ) -> ResultTable:
        """One admitted query's serve path: execute() holds the admission
        grant around this call; `cancel` is the watchdog's kill probe,
        threaded through scatter into every server's between-kernel check."""
        gov = self.governor
        ckey, hit = self._cache_probe(ctx, table, qid, t0)
        if hit is not None:
            return hit
        # schema-aware static validation before scatter: a malformed plan
        # fails ONCE at the broker with a structured error instead of
        # failing per-server inside jit tracing
        from pinot_tpu.analysis.plan_check import check_plan

        with trace.span("plan") as bsp:
            check_plan(ctx, self.coordinator.tables[table].schema)
            self._inject_global_ranges(ctx, table)
            if bsp is not None:
                from pinot_tpu.query.shape import shape_digest

                bsp.annotate(
                    shapeFp=shape_digest(ctx.shape_fingerprint()),
                    resultCache="bypass" if ckey is None else "miss",
                )
                if gov is not None and gov.degrade.level > 0:
                    bsp.annotate(pressure=gov.degrade.level)
        offline_ctx, realtime_ctx = self._split_hybrid(ctx, table)
        meta = self.coordinator.tables[table]
        with trace.span("prune", table=table) as psp:
            seg_names, pruned = self._prune(offline_ctx, table)
        if psp is not None:
            psp.annotate(segments=len(seg_names), pruned=pruned)
        return self._serve_tail(
            ctx, offline_ctx, realtime_ctx, table, meta, seg_names, pruned,
            qid, trace, deadline, t0, cancel, ckey,
        )

    def _serve_tail(
        self,
        ctx: QueryContext,
        offline_ctx: QueryContext,
        realtime_ctx: QueryContext,
        table: str,
        meta,
        seg_names: List[str],
        pruned: int,
        qid: str,
        trace: Trace,
        deadline: Deadline,
        t0: float,
        cancel,
        ckey,
    ) -> ResultTable:
        """Post-prune serve: scatter with full failover, realtime part,
        reduce, finish.  Shared by the sync path, singleton batch members,
        and the per-member fallback when a batched scatter hits a fault."""
        stats = ExecutionStats(num_segments_pruned=pruned)
        results = []
        if seg_names:
            METRICS.gauge("broker.inFlightScatters").add(1)
            try:
                with trace.span("scatter", segments=len(seg_names)):
                    results.extend(
                        self._scatter(
                            offline_ctx, table, seg_names, meta, deadline, stats, trace,
                            cancel=cancel, qid=qid,
                        )
                    )
            finally:
                METRICS.gauge("broker.inFlightScatters").add(-1)
        if any(e.get("errorCode") == "QUERY_KILLED" for e in stats.exceptions):
            # the kill already degraded this query to a partial result —
            # further probes must not re-raise and destroy what survived
            cancel = None
        self._serve_realtime(realtime_ctx, table, qid, cancel, deadline, stats, results, trace)
        return self._finish_result(ctx, table, qid, t0, trace, ckey, results, stats)

    def _cache_probe(self, ctx: QueryContext, table: str, qid: str, t0: float):
        """Result cache lookup: key on the post-resolution fingerprint +
        table version token, BEFORE plan-time option injection mutates
        ctx.  Traced queries bypass it (a cached result carries no spans);
        under memory pressure (degradation level >= 1) the cache is
        bypassed entirely — stop retaining bytes, stop serving stale ones.
        Returns (ckey, hit): ckey is None when caching doesn't apply, hit
        is the stamped cached ResultTable or None."""
        gov = self.governor
        if (
            self._result_cache_enabled(ctx)
            and not ctx.options.get("trace", False)
            and (gov is None or gov.degrade.result_cache_enabled())
        ):
            ckey = (table, ctx.fingerprint(), self._table_version(table))
            hit = self.result_cache.get(ckey)
            if hit is not None:
                import copy

                out = copy.deepcopy(hit)
                out.stats.time_ms = (time.perf_counter() - t0) * 1000
                out.stats.query_id = qid
                out.stats.result_cache = "hit"
                METRICS.histogram("broker.queryLatency").update(out.stats.time_ms)
                return ckey, out
            return ckey, None
        return None, None

    def _split_hybrid(self, ctx: QueryContext, table: str):
        """Hybrid tables (offline segments + a realtime manager under ONE
        name): a TIME BOUNDARY splits the parts — offline answers
        ts <= boundary, realtime answers ts > boundary (TimeBoundaryManager
        analog; late events below the boundary are excluded from the
        realtime part, matching the reference)."""
        offline_ctx, realtime_ctx = ctx, ctx
        meta = self.coordinator.tables[table]
        rt = self.coordinator.realtime.get(table)
        tc = meta.config.segments.time_column
        if rt is not None and meta.ideal and tc:
            ends = [
                sm["timeRange"][1]
                for sm in meta.segment_meta.values()
                if isinstance(sm, dict) and sm.get("timeRange") is not None
            ]
            if ends:
                boundary = max(ends)
                offline_ctx = _with_time_bound(ctx, tc, upper=boundary)
                realtime_ctx = _with_time_bound(ctx, tc, lower_exclusive=boundary)
        return offline_ctx, realtime_ctx

    def _serve_realtime(
        self,
        realtime_ctx: QueryContext,
        table: str,
        qid: str,
        cancel,
        deadline: Deadline,
        stats: ExecutionStats,
        results: List,
        trace: Trace,
    ) -> None:
        """Realtime tables: sealed + consuming segments served from the
        coordinator-owned manager (the RealtimeTableDataManager view).
        Shared by the sync serve path and each batched member (the realtime
        part always executes per member — it is never coalesced)."""
        rt = self.coordinator.realtime.get(table)
        if rt is None:
            return
        from pinot_tpu.query import executor as sse_executor

        with trace.span("realtime") as rsp:
            rt_docs = 0
            for seg in rt.query_segments():
                deadline.check(f"query on {table}")
                if cancel is not None:
                    reason = cancel()
                    if reason:
                        raise QueryKilledError(
                            f"query {qid} killed between realtime segments ({reason})",
                            query_id=qid,
                            reason=reason,
                        )
                stats.num_segments_queried += 1
                stats.total_docs += seg.num_docs
                if sse_executor.prune_segment(realtime_ctx, seg):
                    stats.num_segments_pruned += 1
                    continue
                res, sstats = sse_executor.execute_segment(realtime_ctx, seg)
                stats.num_segments_processed += 1
                stats.num_docs_scanned += sstats.num_docs_scanned
                rt_docs += sstats.num_docs_scanned
                stats.add_index_uses(sstats.filter_index_uses)
                stats.add_kernel_cost(sstats)
                results.append(res)
            if rsp is not None:
                rsp.annotate(docs=rt_docs)

    def _finish_result(
        self,
        ctx: QueryContext,
        table: str,
        qid: str,
        t0: float,
        trace: Trace,
        ckey,
        results: List,
        stats: ExecutionStats,
    ) -> ResultTable:
        """Reduce + response stamping + result-cache populate + latency and
        PerfLedger accounting — the tail every served query (sync or batch
        member) runs through."""
        with trace.span("reduce"):
            out = reduce_mod.reduce_results(ctx, results, stats)
        out.stats.time_ms = (time.perf_counter() - t0) * 1000
        out.stats.query_id = qid
        tr = trace.finish()
        if tr is not None:
            out.stats.trace = tr
        if ckey is not None:
            out.stats.result_cache = "miss"
            # complete answers only: degraded or exception-bearing results
            # must re-execute, never replay
            if not out.stats.partial_result and not out.stats.exceptions:
                import copy

                self.result_cache.put(ckey, copy.deepcopy(out))
        METRICS.histogram("broker.queryLatency").update(out.stats.time_ms)
        from pinot_tpu.query.shape import shape_digest
        from pinot_tpu.utils import perf

        perf.PERF_LEDGER.record(
            table,
            shape_digest(ctx.shape_fingerprint()),
            rows=out.stats.num_docs_scanned,
            time_ms=out.stats.time_ms,
            kernel_bytes=out.stats.kernel_bytes,
            compile_ms=out.stats.compile_ms,
            cache_hit=out.stats.compile_ms == 0.0,
            engine="broker",
        )
        return out

    # -- concurrent serving tier: async submit + cross-query batching ------
    def submit(self, sql: str):
        """Async entry point: returns a concurrent.futures.Future resolving
        to the query's ResultTable (or raising its error).

        Batchable queries (single table, no set-ops/joins/subqueries, not
        EXPLAIN) pay their admission bracket — QPS quota, governor admit +
        watchdog registration, result-cache probe — at submit time, then
        wait up to PINOT_TPU_BATCH_WAIT_MS in the micro-batcher for
        same-shape peers; a coalesced group executes as ONE vmapped launch
        per segment.  Everything else (and every query when the wait window
        is 0) takes the synchronous query() path and comes back as an
        already-completed future, so semantics never change — batching is
        purely an execution strategy."""
        from concurrent.futures import Future

        from pinot_tpu.sql.parser import parse_query
        from pinot_tpu.spi.env import apply_env_defaults

        fut = Future()
        try:
            ctx = parse_query(sql)
            apply_env_defaults(ctx.options)
        except Exception as e:
            fut.set_exception(e)
            return fut
        if not self._batchable(ctx):
            try:
                fut.set_result(self.query(sql))
            except Exception as e:
                fut.set_exception(e)
            return fut
        fp = ctx.fingerprint()
        # literal canonicalization needs column metadata: fingerprint against
        # a representative segment so `v < 5` and `v < 6` share one slot
        # (the same provider plan_segment keys its compile cache with)
        sfp = ctx.shape_fingerprint(self._column_info(ctx.table))
        try:
            member = self._admit_member(ctx, sql, fp, sfp)
        except Exception as e:
            self.slow_queries.record(
                sql, fp, None, error=f"{type(e).__name__}: {e}", shape_fingerprint=sfp
            )
            fut.set_exception(e)
            return fut
        if isinstance(member, ResultTable):  # result-cache hit at submit
            self.slow_queries.record(sql, fp, member, shape_fingerprint=sfp)
            fut.set_result(member)
            return fut
        from pinot_tpu.query.shape import shape_digest

        # the batch key IS the shape fingerprint (digested) — literals
        # differ freely (they ride the stacked params pytree), but options
        # like trace are part of the shape, so traced and untraced queries
        # never coalesce
        return self._batcher().submit((ctx.table, shape_digest(sfp)), member)

    def query_many(self, sqls: List[str]) -> List[ResultTable]:
        """Submit a batch of queries concurrently, flush, and gather —
        errors re-raise in submission order."""
        futs = [self.submit(s) for s in sqls]
        self.drain_batches()
        return [f.result() for f in futs]

    def drain_batches(self) -> int:
        """Flush every pending micro-batch immediately (tests, shutdown,
        synchronous gather)."""
        with self._batcher_lock:
            batcher = self._query_batcher
        if batcher is None:
            return 0
        return batcher.flush()

    def _batcher(self):
        with self._batcher_lock:
            if self._query_batcher is None:
                from pinot_tpu.cluster.batcher import MicroBatcher

                self._query_batcher = MicroBatcher(
                    self._run_batch, clock=self.batch_clock
                )
            return self._query_batcher

    def _column_info(self, table: str):
        """Column-shape provider from any live replica's copy of any
        segment — the audit input shape_fingerprint canonicalizes literals
        with.  None (empty table / nothing routable) keeps literals baked,
        which only means less coalescing, never wrong results."""
        from pinot_tpu.query.shape import column_info_from

        view = self.coordinator.external_view(table)
        for seg, servers in view.items():
            for s in servers:
                srv = self.coordinator.servers.get(s)
                seg_obj = srv.get_segment(table, seg) if srv is not None else None
                if seg_obj is not None:
                    return column_info_from(seg_obj)
        return None

    def _batchable(self, ctx: QueryContext) -> bool:
        """Only plain single-table scans coalesce; compound shapes keep the
        recursive synchronous path (their sub-plans pay their own quota and
        admission there)."""
        if ctx.options.get("__explain__") or ctx.options.get("__analyze__"):
            return False
        if ctx.set_ops or ctx.joins:
            return False
        if ctx.table not in self.coordinator.tables:
            return False
        return not _has_subquery(ctx.filter) and not _has_subquery(
            getattr(ctx, "having", None)
        )

    def _admit_member(self, ctx: QueryContext, sql: str, fp: str, sfp: str):
        """The pre-batch slice of execute(): quota, query id, trace root,
        deadline, governor admission, cache probe.  Returns a cached
        ResultTable on a hit, else a _BatchMember holding the live grant
        (closed by _member_done on every completion path)."""
        table = ctx.table
        self.quota.check(
            table, self.coordinator.tables[table].config.max_queries_per_second
        )
        t0 = time.perf_counter()
        deadline = Deadline.from_ctx(ctx)
        qid = f"{self._broker_id}_{next(self._qid_seq)}"
        trace = Trace(bool(ctx.options.get("trace", False)), query_id=qid)
        METRICS.counter("broker.queries").inc()
        grant = None
        cancel = None
        gov = self.governor
        if gov is not None:
            cost = estimate_query_cost(
                ctx, self.coordinator.tables[table].segment_meta.values()
            )
            grant = gov.admit(qid, ctx, cost, deadline)
            cancel = gov.cancel_probe(qid)
        try:
            ckey, hit = self._cache_probe(ctx, table, qid, t0)
        except Exception:
            if grant is not None:
                grant.close()
            raise
        if hit is not None:
            if grant is not None:
                grant.close()
            return hit
        return _BatchMember(
            ctx=ctx, sql=sql, fp=fp, sfp=sfp, qid=qid, trace=trace,
            deadline=deadline, t0=t0, grant=grant, cancel=cancel, ckey=ckey,
        )

    def _run_batch(self, entries) -> None:
        """MicroBatcher runner: one coalesced group of same-shape members.
        Owns completion — every entry's future resolves here."""
        if len(entries) == 1:
            m = entries[0].payload
            self._member_done(entries[0], self._serve_member(m))
            return
        members = [e.payload for e in entries]
        try:
            outcomes = self._serve_batch(members)
        except Exception as exc:  # orchestration safety net: never hang a future
            outcomes = [exc] * len(members)
        for entry, out in zip(entries, outcomes):
            self._member_done(entry, out)

    def _member_done(self, entry, outcome) -> None:
        """Deliver one member's outcome: slow-log entry, future resolution,
        admission grant release."""
        m = entry.payload
        try:
            if isinstance(outcome, BaseException):
                self.slow_queries.record(
                    m.sql, m.fp, None,
                    error=f"{type(outcome).__name__}: {outcome}",
                    shape_fingerprint=m.sfp,
                )
                entry.future.set_exception(outcome)
            else:
                self.slow_queries.record(m.sql, m.fp, outcome, shape_fingerprint=m.sfp)
                entry.future.set_result(outcome)
        finally:
            if m.grant is not None:
                m.grant.close()

    def _serve_member(self, m) -> object:
        """Plan + prune + serve ONE pre-admitted member through the standard
        failover path (singleton flushes and post-fault fallbacks).  Returns
        the ResultTable or the exception — never raises."""
        table = m.ctx.table
        try:
            meta = self.coordinator.tables[table]
            self._plan_member(m, table, meta)
            return self._serve_tail(
                m.ctx, m.offline_ctx, m.realtime_ctx, table, meta,
                m.seg_names, m.pruned, m.qid, m.trace, m.deadline, m.t0,
                m.cancel, m.ckey,
            )
        except Exception as e:
            # outcome, not a swallow: _member_done slow-logs it and fails
            # the submitter's future
            METRICS.counter("broker.memberServeErrors").inc()
            return e

    def _plan_member(self, m, table: str, meta) -> None:
        """The plan-span + hybrid-split + prune slice of _serve, recorded on
        the member's own trace."""
        from pinot_tpu.analysis.plan_check import check_plan

        gov = self.governor
        with m.trace.span("plan") as bsp:
            check_plan(m.ctx, meta.schema)
            self._inject_global_ranges(m.ctx, table)
            if bsp is not None:
                from pinot_tpu.query.shape import shape_digest

                bsp.annotate(
                    shapeFp=shape_digest(m.ctx.shape_fingerprint()),
                    resultCache="bypass" if m.ckey is None else "miss",
                )
                if gov is not None and gov.degrade.level > 0:
                    bsp.annotate(pressure=gov.degrade.level)
        m.offline_ctx, m.realtime_ctx = self._split_hybrid(m.ctx, table)
        with m.trace.span("prune", table=table) as psp:
            m.seg_names, m.pruned = self._prune(m.offline_ctx, table)
        if psp is not None:
            psp.annotate(segments=len(m.seg_names), pruned=m.pruned)

    def _serve_batch(self, members: List) -> List:
        """Serve one coalesced same-shape group: per-member plan/prune, then
        sub-group by IDENTICAL pruned segment list (prune divergence never
        mis-attributes work), one batched scatter per sub-group, and the
        per-member realtime/reduce/finish tail.  Returns one outcome
        (ResultTable or Exception) per member; a transport-level fault in a
        batched scatter falls the affected sub-group back to the standard
        per-member failover path instead of failing anyone."""
        table = members[0].ctx.table
        meta = self.coordinator.tables[table]
        batch_id = f"b{self._broker_id}_{next(self._qid_seq)}"
        outcomes: List = [None] * len(members)
        groups: Dict[Tuple, List[int]] = {}
        for i, m in enumerate(members):
            try:
                self._plan_member(m, table, meta)
                m.trace.annotate(batchId=batch_id, batchSize=len(members))
                groups.setdefault(tuple(m.seg_names), []).append(i)
            except Exception as e:
                # recorded as the member's outcome; _member_done slow-logs it
                METRICS.counter("broker.memberServeErrors").inc()
                outcomes[i] = e
        METRICS.counter("broker.batches").inc()
        METRICS.histogram("broker.batchSize").update(len(members))
        for segs, idxs in groups.items():
            group = [members[i] for i in idxs]
            if len(idxs) == 1 or not segs:
                # lone segment-list (or pure-realtime query): nothing to
                # coalesce at the kernel layer — standard path
                for i in idxs:
                    outcomes[i] = self._serve_member(members[i])
                continue
            try:
                res_lists, stats_list, errs = self._scatter_batch(
                    group, table, list(segs), meta, batch_id
                )
            except Exception:
                # batch-level fault (server crash, capacity, routing): the
                # whole sub-group re-executes individually with the full
                # failover machinery — batching is bypassed on faults
                METRICS.counter("broker.batchFallbacks").inc()
                for i in idxs:
                    outcomes[i] = self._serve_member(members[i])
                continue
            for i, m, results, stats, err in zip(
                idxs, group, res_lists, stats_list, errs
            ):
                outcomes[i] = self._finish_batch_member(
                    m, table, results, stats, err
                )
        return outcomes

    def _finish_batch_member(self, m, table, results, stats, err):
        """Realtime part + reduce + finish for one batched member, honoring
        the kill/timeout taxonomy: a detached member degrades to a partial
        result when it opted in, else its error is its outcome."""
        allow_partial = str(m.ctx.options.get("allowPartialResults", "")).lower() in (
            "1", "true", "yes",
        )
        try:
            cancel = m.cancel
            if err is not None:
                if isinstance(err, QueryKilledError):
                    METRICS.counter("broker.queriesKilled").inc()
                if not allow_partial:
                    return err
                stats.partial_result = True
                stats.exceptions.append(
                    {
                        "errorCode": "QUERY_KILLED"
                        if isinstance(err, QueryKilledError)
                        else "EXECUTION_TIMEOUT_ERROR",
                        "message": str(err),
                    }
                )
                METRICS.counter("broker.partialResults").inc()
                cancel = None  # the kill already degraded this member
            self._serve_realtime(
                m.realtime_ctx, table, m.qid, cancel, m.deadline, stats,
                results, m.trace,
            )
            return self._finish_result(
                m.ctx, table, m.qid, m.t0, m.trace, m.ckey, results, stats
            )
        except Exception as e:
            # outcome, not a swallow: the caller fails this member's future
            METRICS.counter("broker.memberServeErrors").inc()
            return e

    # -- hedged execution (tail tolerance) ---------------------------------
    @staticmethod
    def _compose_cancel(base, lost_evt):
        """Per-attempt cancel probe for a hedged call: the outer watchdog
        probe (if any) keeps priority; once the sibling attempt wins, the
        probe returns "hedge_lost" and the loser abandons its pending
        launches through the SAME cooperative path a watchdog kill uses
        (ServerInstance._check_budget between kernels)."""

        def probe():
            if base is not None:
                r = base()
                if r:
                    return r
            if lost_evt.is_set():
                return "hedge_lost"
            return None

        return probe

    def _hedge_target(
        self, table: str, segs: List[str], primary: str, exclude: frozenset
    ) -> Optional[str]:
        """Best alternative replica serving ALL of the primary's segments:
        live, breaker-available, not the primary, not excluded this scatter.
        Non-browned closed-breaker replicas rank first, then adaptive score —
        hedging onto a gray server would just move the tail."""
        view = self.coordinator.external_view(table)
        candidates: Optional[Set[str]] = None
        for seg in segs:
            replicas = view.get(seg, set())
            candidates = set(replicas) if candidates is None else (candidates & replicas)
            if not candidates:
                return None
        if not candidates:
            return None
        candidates = {
            s
            for s in candidates
            if s != primary
            and s not in exclude
            and s in self.coordinator.live
            and self.health.available(s)
        }
        if not candidates:
            return None
        return min(
            candidates,
            key=lambda s: (
                self.health.brownout_deprioritized(s),
                self.health.state(s) != "closed",
                self.server_stats.score(s),
                s,
            ),
        )

    def hedge_drain(self, timeout_s: float = 5.0) -> int:
        """Join every outstanding hedge attempt thread; returns how many are
        STILL alive after the timeout (tests assert 0 — no leaked launches)."""
        with self._hedge_lock:
            threads = list(self._hedge_threads)
        dl = time.monotonic() + timeout_s
        alive = 0
        for t in threads:
            t.join(timeout=max(0.0, dl - time.monotonic()))
            if t.is_alive():
                alive += 1
        return alive

    def _account_loser(
        self, name: str, ok: bool, out, ms: float, table: str, stats, batch: bool = False
    ) -> None:
        """Settle the attempt that did NOT win a hedged call — accounting
        happens here EXACTLY once (the winner path never sees the loser).
        Runs on the loser's own thread (or the caller's, for a failure that
        arrived before the winner)."""
        if ok and batch:
            # a losing execute_batch returns normally with each member
            # detached via its probe: all-members hedge_lost == cancelled
            errs = out[2]
            if errs and all(
                isinstance(e, QueryKilledError) and getattr(e, "reason", None) == "hedge_lost"
                for e in errs
            ):
                METRICS.counter("broker.hedgesCancelled").inc()
                METRICS.timer("broker.hedgeCancelMs").update(ms)
                return
        if ok:
            # the loser finished anyway (too late to matter): its latency is
            # real signal, its work is the hedge's waste
            self.health.record_success(name)
            self.health.note_latency(name, ms)
            self.hedge.observe(table, name, ms)
            METRICS.timer("broker.hedgeWastedMs").update(ms)
            return
        e = out
        if isinstance(e, QueryKilledError) and e.reason == "hedge_lost":
            # cooperative cancel landed: not a failure — no punish, breaker
            # untouched (mirrors the watchdog-kill taxonomy in _scatter)
            METRICS.counter("broker.hedgesCancelled").inc()
            METRICS.timer("broker.hedgeCancelMs").update(ms)
            if stats is not None:
                stats.hedge_cancelled_ms = ms  # best-effort slowlog surface
            return
        if isinstance(e, QueryKilledError):
            return  # outer watchdog kill: canonical accounting rides the winner path
        if isinstance(e, ReservationError):
            METRICS.counter("broker.scatterCapacityRejections").inc()
            return
        # genuine fault on the losing attempt: punish/breaker exactly once,
        # here (its segments were served by the winner — no failover needed)
        self.server_stats.punish(name)
        self.health.record_failure(name)
        METRICS.counter("broker.scatterServerFailures").inc()

    def _hedged_call(
        self,
        table: str,
        primary: str,
        run,
        *,
        opts: Optional[Dict] = None,
        segs: List[str] = (),
        exclude: frozenset = frozenset(),
        stats=None,
        batch: bool = False,
    ):
        """Run ``run(server, lost_event)`` on `primary`, hedging a backup
        replica when the quantile-derived delay elapses without a reply.
        Returns ``(winner, payload, winner_ms, info)``.

        Engagement is decided up front: hedging must be enabled (env/option),
        a delay must be derivable (enough peer samples or an override), a
        spare replica must cover the segments, and firing must clear both
        the hedge budget and a non-blocking admission charge — otherwise the
        call runs inline on the caller's thread exactly like the unhedged
        scatter path (no threads, no behavior change).

        First SUCCESS wins; the loser is cancelled through its cancel probe
        and settles itself via _account_loser.  A failure that arrives while
        the sibling is still in flight is held: if the sibling succeeds it
        becomes the winner (the failure is side-accounted exactly once); if
        both fail the PRIMARY's error propagates so the outer failover arms
        attribute it to the routed server exactly as before."""
        hc = self.hedge
        hc.note_primary()
        info: Dict = {"hedged": False, "winner": None, "delay_ms": None, "hedge_server": None}
        delay = None
        target = None
        if hc.enabled(opts):
            delay = hc.delay_ms(table, primary, opts)
            if delay is not None:
                target = self._hedge_target(table, segs, primary, exclude)
        if delay is None or target is None:
            # inline fast path: identical to the pre-hedge scatter call
            self.server_stats.begin(primary)
            st0 = time.perf_counter()
            try:
                payload = run(primary, None)
            except Exception:
                self.server_stats.end(primary, (time.perf_counter() - st0) * 1000)
                raise
            ms = (time.perf_counter() - st0) * 1000
            self.server_stats.end(primary, ms)
            hc.observe(table, primary, ms)
            return primary, payload, ms, info

        result_q: "queue.Queue" = queue.Queue()
        slock = threading.Lock()
        state: Dict[str, Optional[str]] = {"winner": None}
        lost = {primary: threading.Event(), target: threading.Event()}

        def attempt(name: str) -> None:
            try:
                self.server_stats.begin(name)
                st0 = time.perf_counter()
                try:
                    out, ok = run(name, lost[name]), True
                # not swallowed: the captured exception is triaged by the
                # consumer (winner path raises it, loser path accounts it)
                except Exception as e:  # pinot-lint: disable=W006
                    out, ok = e, False
                ms = (time.perf_counter() - st0) * 1000
                self.server_stats.end(name, ms)
                with slock:
                    if state["winner"] is None:
                        if ok:
                            state["winner"] = name
                        result_q.put((name, ok, out, ms))
                        return
                # a sibling already won: this attempt lost — settle off-path
                self._account_loser(name, ok, out, ms, table, stats, batch=batch)
            finally:
                with self._hedge_lock:
                    self._hedge_threads.discard(threading.current_thread())

        def spawn(name: str, role: str) -> None:
            t = threading.Thread(
                target=attempt, args=(name,), daemon=True, name=f"hedge-{role}-{name}"
            )
            with self._hedge_lock:
                self._hedge_threads.add(t)
            t.start()

        spawn(primary, "primary")
        hedge_fired = False
        try:
            first = result_q.get(timeout=delay / 1000.0)
        except queue.Empty:
            first = None
            # primary is past the derived delay: fire the backup if the
            # hedge budget AND a non-blocking admission charge both clear
            denied = None
            if not hc.try_fire(opts):
                denied = "budget"
            elif self.governor is not None and not self.governor.try_charge_hedge(1.0):
                hc.unfire()
                denied = "admission"
            if denied is None:
                hedge_fired = True
                info.update(hedged=True, delay_ms=delay, hedge_server=target)
                METRICS.counter("broker.hedgesLaunched").inc()
                spawn(target, "backup")
            else:
                info["denied"] = denied
                METRICS.counter("broker.hedgesDenied").inc()
        if first is None:
            first = result_q.get()
        name, ok, out, ms = first
        if not ok and hedge_fired:
            # one attempt failed while its sibling is still running: the
            # sibling IS the retry — hold the error until it reports
            name2, ok2, out2, ms2 = result_q.get()
            if ok2:
                self._account_loser(name, False, out, ms, table, stats, batch=batch)
                name, ok, out, ms = name2, True, out2, ms2
            else:
                # both failed: side-account the backup, raise the primary's
                # error so the outer taxonomy keys on the routed server
                prim_err, hedge_err = (out, out2) if name == primary else (out2, out)
                hedge_ms = ms2 if name == primary else ms
                self._account_loser(target, False, hedge_err, hedge_ms, table, stats, batch=batch)
                raise prim_err
        if not ok:
            raise out  # no hedge in flight: identical to the inline path
        winner = name
        for other, evt in lost.items():
            if other != winner:
                evt.set()
        info["winner"] = winner
        hc.observe(table, winner, ms)
        if hedge_fired and winner == target:
            METRICS.counter("broker.hedgeWins").inc()
        return winner, out, ms, info

    def _scatter_batch(self, group: List, table: str, seg_names: List[str], meta, batch_id: str):
        """Failover-free batched scatter: route ONCE for the whole
        sub-group, run server.execute_batch per routed server (one vmapped
        launch per segment), and accumulate per-member stats.  Per-member
        kill/deadline errors come back in the errors list (siblings keep
        their exact results); any transport-level fault raises so the
        caller falls the sub-group back to the standard path — after
        recording it on the breaker, so the retry routes around the bad
        server."""
        n = len(group)
        # whole-batch replica-row pinning: every member of a same-fingerprint
        # batch routes to ONE replica group (mesh replica row), and batches
        # round-robin across rows — concurrent QPS scales with row count
        # while each row serves its batch from one staged copy
        with self._rr_lock:
            prefer = self._batch_rr
            self._batch_rr += 1
        assign = self._route(table, seg_names, prefer_group=prefer)
        trace_on = any(m.trace.enabled for m in group)
        results: List[list] = [[] for _ in range(n)]
        stats = [ExecutionStats(num_segments_pruned=m.pruned) for m in group]
        member_errs: List[Optional[Exception]] = [None] * n
        per_call = []
        for m in group:
            sto = m.ctx.options.get("serverTimeoutMs")
            per_call.append(
                m.deadline.bounded(float(sto) if sto is not None else None)
            )
        queried = 0
        responded = 0
        METRICS.gauge("broker.inFlightScatters").add(1)
        try:
            for server_name, segs in assign.items():
                queried += 1

                def run_batch(name, lost_evt, _segs=segs):
                    srv = self.coordinator.servers[name]
                    # per-member isolation survives hedging: each member's own
                    # watchdog probe keeps priority inside the composed probe
                    comp = (
                        [m.cancel for m in group]
                        if lost_evt is None
                        else [self._compose_cancel(m.cancel, lost_evt) for m in group]
                    )
                    return srv.execute_batch(
                        [m.offline_ctx for m in group],
                        _segs,
                        table_schema=meta.schema,
                        deadlines=per_call,
                        cancels=comp,
                        batch_id=batch_id,
                        trace_enabled=trace_on,
                    )

                try:
                    winner, _payload, win_ms, hinfo = self._hedged_call(
                        table, server_name, run_batch,
                        opts=group[0].ctx.options, segs=segs, batch=True,
                    )
                    res, sstats, errs, btrace = _payload
                except Exception as e:
                    if not isinstance(e, ReservationError):
                        # genuine fault: breaker + adaptive stats learn it so
                        # the per-member fallback routes around this server
                        self.server_stats.punish(server_name)
                        self.health.record_failure(server_name)
                        METRICS.counter("broker.scatterServerFailures").inc()
                    else:
                        METRICS.counter("broker.scatterCapacityRejections").inc()
                    raise
                self.health.record_success(winner)
                transition = self.health.note_latency(winner, win_ms)
                if hinfo["hedged"] or transition is not None:
                    for st in stats:
                        if hinfo["hedged"]:
                            st.hedged += 1
                            st.hedge_winner = winner
                        if transition is not None:
                            st.brownout_events.append(f"{transition}:{winner}")
                if hinfo["hedged"]:
                    queried += 1
                responded += 1
                for i in range(n):
                    if errs[i] is not None:
                        if member_errs[i] is None:
                            member_errs[i] = errs[i]
                        continue
                    results[i].extend(res[i])
                    stats[i].num_segments_queried += sstats[i].num_segments_queried
                    stats[i].num_segments_processed += sstats[i].num_segments_processed
                    stats[i].num_segments_pruned += sstats[i].num_segments_pruned
                    stats[i].num_docs_scanned += sstats[i].num_docs_scanned
                    stats[i].total_docs += sstats[i].total_docs
                    stats[i].add_index_uses(sstats[i].filter_index_uses)
                    stats[i].add_kernel_cost(sstats[i])
                if btrace is not None:
                    import copy

                    for k, m in enumerate(group):
                        if m.trace.enabled:
                            m.trace.graft(copy.deepcopy(btrace))
        finally:
            METRICS.gauge("broker.inFlightScatters").add(-1)
            for i in range(n):
                stats[i].num_servers_queried = queried
                stats[i].num_servers_responded = responded
        return results, stats, member_errs

    # -- fault-tolerant scatter-gather ------------------------------------
    def _scatter(
        self,
        ctx: QueryContext,
        table: str,
        seg_names: List[str],
        meta,
        deadline: Deadline,
        stats: ExecutionStats,
        trace: Optional[Trace] = None,
        cancel=None,
        qid: Optional[str] = None,
    ) -> List:
        """Deadline-budgeted scatter with replica failover (the
        QueryRouter.submitQuery + BaseSingleStageBrokerRequestHandler retry
        contract, in-process).

        Each routed server gets the query's remaining budget, optionally
        capped by the serverTimeoutMs option.  A failed or timed-out server
        is excluded, trips the circuit breaker one notch, and its segments
        re-route to surviving replicas (bounded rounds, jittered backoff).
        When a segment has no replica left: with allowPartialResults=true
        the response degrades (partialResult=true + exception entries +
        numServersResponded < numServersQueried); otherwise the query fails
        with the collected per-server exceptions.

        Tracing: each failover round gets a `round:N` span; each routed call
        a `server_execute` span (server, round, probe, error, breaker state)
        with the server's own finished subtree grafted beneath it — the
        retry/breaker machinery is visible in ONE tree per query.

        Governance faults are NOT server faults: a ReservationError (server
        at HBM capacity) fails the segments over to another replica without
        punishing the adaptive stats or tripping the breaker — capacity
        returns when queries drain, quarantine would amplify the overload;
        when EVERY replica is out of capacity the query fails structured
        503 SERVER_OUT_OF_CAPACITY.  A QueryKilledError (watchdog) punishes
        the adaptive stats exactly once, leaves the breaker untouched, and
        either degrades to a partial result (allowPartialResults) or
        re-raises as a structured QUERY_KILLED failure."""
        if trace is None:
            trace = Trace(False)
        opts = ctx.options
        allow_partial = str(opts.get("allowPartialResults", "")).lower() in ("1", "true", "yes")
        max_retries = int(opts.get("maxScatterRetries", 2))
        backoff_ms = float(opts.get("scatterBackoffMs", 2.0))
        server_timeout_ms = opts.get("serverTimeoutMs")
        results: List = []
        excluded: Set[str] = set()
        queried: Set[str] = set()
        responded: Set[str] = set()
        pending = list(seg_names)
        rounds = 0
        killed = False  # watchdog kill absorbed as a partial result
        capacity_rejections = 0  # ReservationError count this scatter
        non_capacity_failure = False  # any genuine server fault seen
        try:
            while pending:
                with trace.span(f"round:{rounds}", segments=len(pending)):
                    assign, unroutable = self._route(
                        table, pending, exclude=frozenset(excluded), partial_ok=True
                    )
                    if unroutable:
                        if capacity_rejections and not non_capacity_failure and not allow_partial:
                            # every replica was excluded for CAPACITY, not
                            # faults: surface the overload signal (503
                            # SERVER_OUT_OF_CAPACITY), not "no live replica"
                            raise ReservationError(
                                f"segment(s) {sorted(unroutable)} of table {table!r}: "
                                f"every replica out of capacity",
                                query_id=qid,
                            )
                        self._absorb_unroutable(table, unroutable, excluded, allow_partial, stats)
                    failed: List[str] = []
                    for server_name, segs in assign.items():
                        deadline.check(f"query on {table}")
                        queried.add(server_name)
                        probe = self.health.state(server_name) == "half_open"
                        self.health.begin_probe(server_name)  # no-op unless half-open
                        per_call = deadline.bounded(
                            float(server_timeout_ms) if server_timeout_ms is not None else None
                        )

                        def run_one(name, lost_evt, _segs=segs, _per_call=per_call):
                            srv = self.coordinator.servers[name]
                            comp = (
                                cancel if lost_evt is None
                                else self._compose_cancel(cancel, lost_evt)
                            )
                            return srv.execute(
                                ctx, _segs, table_schema=meta.schema,
                                deadline=_per_call, cancel=comp,
                            )

                        with trace.span(
                            "server_execute", server=server_name, segments=len(segs),
                            round=rounds, probe=probe,
                        ) as ssp:
                            try:
                                winner, payload, win_ms, hinfo = self._hedged_call(
                                    table, server_name, run_one, opts=opts, segs=segs,
                                    exclude=frozenset(excluded), stats=stats,
                                )
                                res, sstats = payload
                            except Exception as e:  # noqa: BLE001 — every fault is recorded below
                                if isinstance(e, QueryTimeoutError) and deadline.expired():
                                    raise  # the QUERY is out of budget, not just this server
                                if isinstance(e, QueryKilledError):
                                    # watchdog kill: punish the adaptive stats
                                    # EXACTLY once (the killed query consumed
                                    # this server's time), breaker untouched
                                    # (the server is healthy — the query died)
                                    self.server_stats.punish(server_name)
                                    METRICS.counter("broker.queriesKilled").inc()
                                    stats.exceptions.append(
                                        {
                                            "errorCode": "QUERY_KILLED",
                                            "message": f"server {server_name}: {e}",
                                            "server": server_name,
                                            "reason": e.reason,
                                        }
                                    )
                                    if ssp is not None:
                                        ssp.annotate(killed=e.reason)
                                    if allow_partial:
                                        stats.partial_result = True
                                        METRICS.counter("broker.partialResults").inc()
                                        killed = True
                                        break  # surviving results ship as-is
                                    e.query_id = qid
                                    raise
                                if isinstance(e, ReservationError):
                                    # capacity, not a fault: fail the segments
                                    # over without punishing or opening the
                                    # breaker (quarantining a full server
                                    # would amplify the overload)
                                    excluded.add(server_name)
                                    failed.extend(segs)
                                    capacity_rejections += 1
                                    stats.exceptions.append(
                                        {
                                            "errorCode": "SERVER_OUT_OF_CAPACITY",
                                            "message": f"server {server_name}: {e}",
                                            "server": server_name,
                                        }
                                    )
                                    METRICS.counter("broker.scatterCapacityRejections").inc()
                                    if ssp is not None:
                                        ssp.annotate(capacity="rejected")
                                    continue
                                non_capacity_failure = True
                                self.server_stats.punish(server_name)
                                self.health.record_failure(server_name)
                                excluded.add(server_name)
                                failed.extend(segs)
                                stats.exceptions.append(
                                    {
                                        "errorCode": "EXECUTION_TIMEOUT_ERROR"
                                        if isinstance(e, QueryTimeoutError)
                                        else "SERVER_SCATTER_ERROR",
                                        "message": f"server {server_name}: {type(e).__name__}: {e}",
                                        "server": server_name,
                                    }
                                )
                                METRICS.counter("broker.scatterServerFailures").inc()
                                if ssp is not None:
                                    ssp.annotate(
                                        error=f"{type(e).__name__}: {e}",
                                        breaker=self.health.state(server_name),
                                    )
                                continue
                            # the winner may be the hedged backup, not the
                            # routed primary: success accounting keys on it
                            self.health.record_success(winner)
                            transition = self.health.note_latency(winner, win_ms)
                            if transition is not None:
                                stats.brownout_events.append(f"{transition}:{winner}")
                                if ssp is not None:
                                    ssp.annotate(brownout=f"{transition}:{winner}")
                            if hinfo["hedged"]:
                                stats.hedged += 1
                                stats.hedge_winner = winner
                                queried.add(hinfo["hedge_server"])
                                if ssp is not None:
                                    ssp.annotate(
                                        hedged=True,
                                        winner=winner,
                                        hedgeDelayMs=round(hinfo["delay_ms"], 3),
                                    )
                            responded.add(winner)
                            results.extend(res)
                            stats.num_segments_queried += sstats.num_segments_queried
                            stats.num_segments_processed += sstats.num_segments_processed
                            stats.num_segments_pruned += sstats.num_segments_pruned
                            stats.num_docs_scanned += sstats.num_docs_scanned
                            stats.total_docs += sstats.total_docs
                            stats.add_index_uses(sstats.filter_index_uses)
                            stats.add_kernel_cost(sstats)
                            trace.graft(sstats.trace)
                            if ssp is not None:
                                ssp.annotate(docs=sstats.num_docs_scanned)
                pending = failed
                if killed:
                    break  # partial-result kill: no failover for what's left
                if pending:
                    rounds += 1
                    if rounds > max_retries:
                        msg = (
                            f"segments {sorted(pending)} of table {table!r} failed on every "
                            f"tried replica after {max_retries} failover round(s)"
                        )
                        if not allow_partial:
                            if capacity_rejections and not non_capacity_failure:
                                # every tried replica was at capacity: this is
                                # an overload rejection, not a scatter fault
                                raise ReservationError(
                                    f"{msg}: every replica out of capacity",
                                    query_id=qid,
                                )
                            raise ScatterGatherError(msg, stats.exceptions)
                        stats.partial_result = True
                        stats.exceptions.append(
                            {"errorCode": "PARTIAL_RESPONSE", "message": msg}
                        )
                        METRICS.counter("broker.partialResults").inc()
                        break
                    deadline.check(f"query on {table}")
                    if backoff_ms > 0:
                        # exponential backoff with full jitter (seeded rng)
                        self._sleep(
                            backoff_ms
                            * (2 ** (rounds - 1))
                            * (0.5 + self.retry_rng.random() / 2)
                            / 1000.0
                        )
        finally:
            stats.num_servers_queried = len(queried)
            stats.num_servers_responded = len(responded)
        return results

    def _absorb_unroutable(
        self,
        table: str,
        unroutable: List[str],
        excluded: Set[str],
        allow_partial: bool,
        stats: ExecutionStats,
    ) -> None:
        """Segments with no routable replica: degrade to a partial result
        when the query opted in, else fail with the routing detail."""
        detail = f" (failed/excluded servers: {sorted(excluded)})" if excluded else ""
        msg = (
            f"segment(s) {sorted(unroutable)} of table {table!r} have no live replica{detail}"
        )
        if not allow_partial:
            raise NoReplicaAvailableError(msg)
        stats.partial_result = True
        stats.exceptions.append({"errorCode": "NO_REPLICA_AVAILABLE", "message": msg})
        METRICS.counter("broker.partialResults").inc()

    # -- cluster metric federation (tentpole r9c) -------------------------
    def federated_registries(self):
        """name -> per-server MetricsRegistry for every registered server —
        the scrape set the broker federates (BrokerMetrics pulling
        ServerMetrics; here a method call instead of an HTTP scrape)."""
        return {
            name: srv.metrics
            for name, srv in self.coordinator.servers.items()
            if getattr(srv, "metrics", None) is not None
        }

    def federated_prometheus(self) -> str:
        """Cluster-wide Prometheus exposition: this broker process's own
        registry (unlabeled, as before) plus every server's registry as
        `{server="..."}`-labeled series and `pinot_cluster_*` merged
        aggregates — `GET /metrics?format=prometheus` describes the
        cluster, not one process."""
        from pinot_tpu.utils.metrics import federate_prometheus

        return METRICS.to_prometheus() + federate_prometheus(self.federated_registries())

    def federated_snapshot(self):
        """JSON twin of federated_prometheus: per-server snapshots plus the
        merged cluster view (sum/max/last semantics per metric type)."""
        from pinot_tpu.utils.metrics import merge_registry_snapshots

        regs = self.federated_registries()
        return {
            "perServer": {name: reg.snapshot() for name, reg in regs.items()},
            "cluster": merge_registry_snapshots(regs),
        }

    def perf_snapshot(self):
        """Per-table/per-shape perf ledger view (GET /debug/perf), plus the
        live named-cache occupancy (plan caches, result cache)."""
        from pinot_tpu.utils.cache import named_cache_stats
        from pinot_tpu.utils.perf import PERF_LEDGER

        snap = PERF_LEDGER.snapshot()
        snap["caches"] = named_cache_stats()
        return snap

    def _explain(self, ctx: QueryContext) -> ResultTable:
        """EXPLAIN PLAN FOR through the broker: reuse the engine explain
        against one representative segment (no execution)."""
        from pinot_tpu.query.engine import QueryEngine

        meta = self.coordinator.tables[ctx.table]
        segs = []
        for name in meta.ideal:  # first segment with a LIVE replica
            obj = self.coordinator._find_segment_object(ctx.table, name, self.coordinator.live)
            if obj is not None:
                segs.append(obj)
                break
        if not segs:
            rt = self.coordinator.realtime.get(ctx.table)
            if rt is not None:
                segs = rt.query_segments()[:1]
        shim = QueryEngine()
        shim.register_table(meta.schema, meta.config)
        return shim._explain(ctx, segs)

    def _explain_analyze(self, ctx: QueryContext) -> ResultTable:
        """EXPLAIN ANALYZE: run the query with tracing forced, then join the
        static operator tree with the measured span tree (query.analyze)."""
        from pinot_tpu.query.analyze import analyze_result

        ctx.options.pop("__analyze__", None)
        ctx.options["trace"] = True
        for _op, _all, rhs in ctx.set_ops:
            rhs.options.pop("__analyze__", None)
            rhs.options["trace"] = True
        executed = self.execute(ctx)
        return analyze_result(self._explain(ctx), executed)

    def _inject_global_ranges(self, ctx: QueryContext, table: str) -> None:
        """Table-global sketch constants from broker-side metadata (the
        QueryEngine does the same from segment objects)."""
        from pinot_tpu.query.functions import for_spec

        meta = self.coordinator.tables[table]
        for spec in ctx.aggregations:
            if spec.expr is None or not spec.expr.is_column:
                continue
            if not for_spec(spec).needs_binding:
                continue
            col = spec.expr.op
            rkey, fkey = f"__range__{col}", f"__dictfp__{col}"
            if rkey in ctx.options and fkey in ctx.options:
                continue
            mins, maxs, fps = [], [], set()
            for sm in meta.segment_meta.values():
                cs = sm.get("colStats", {}).get(col)
                if cs is None:
                    continue
                fps.add(cs["dictFp"])
                if cs["min"] is not None and not isinstance(cs["min"], str):
                    mins.append(cs["min"])
                    maxs.append(cs["max"])
            if mins:
                ctx.options.setdefault(rkey, (min(mins), max(maxs)))
            if fps:
                only = next(iter(fps)) if len(fps) == 1 else None
                ctx.options.setdefault(fkey, "MIXED" if len(fps) > 1 else (only or ""))


def _with_time_bound(ctx: QueryContext, time_column: str, upper=None, lower_exclusive=None) -> QueryContext:
    """ctx with an extra AND bound on the time column (hybrid-table split)."""
    import dataclasses

    from pinot_tpu.query.ir import Expr, Predicate

    if upper is not None:
        pred = Predicate(PredicateType.RANGE, Expr.col(time_column), upper=upper)
    else:
        pred = Predicate(
            PredicateType.RANGE, Expr.col(time_column), lower=lower_exclusive, lower_inclusive=False
        )
    node = FilterNode.pred(pred)
    f = node if ctx.filter is None else FilterNode.and_(ctx.filter, node)
    return dataclasses.replace(ctx, filter=f)


# ---------------------------------------------------------------------------
# filter-shape helpers for pruners
# ---------------------------------------------------------------------------
def _eq_values_by_column(node: Optional[FilterNode]) -> Dict[str, List]:
    """Top-level AND-path EQ/IN values per column (conservative: OR subtrees
    are ignored — pruning must never drop a segment that could match)."""
    out: Dict[str, List] = {}

    def walk(n: Optional[FilterNode]) -> None:
        if n is None:
            return
        if n.op is FilterOp.AND:
            for c in n.children:
                walk(c)
        elif n.op is FilterOp.PRED and n.predicate is not None:
            p = n.predicate
            if p.lhs.is_column and p.ptype in (PredicateType.EQ, PredicateType.IN):
                out.setdefault(p.lhs.op, []).extend(p.values)

    walk(node)
    return out


def _range_for_column(node: Optional[FilterNode], col: str) -> Tuple[Optional[float], Optional[float]]:
    """Top-level AND-path [lo, hi] bound for one column, None = unbounded."""
    lo = hi = None

    def walk(n: Optional[FilterNode]) -> None:
        nonlocal lo, hi
        if n is None:
            return
        if n.op is FilterOp.AND:
            for c in n.children:
                walk(c)
        elif n.op is FilterOp.PRED and n.predicate is not None:
            p = n.predicate
            if not (p.lhs.is_column and p.lhs.op == col):
                return
            if p.ptype is PredicateType.EQ:
                lo = hi = p.values[0]
            elif p.ptype is PredicateType.RANGE:
                if p.lower is not None:
                    lo = p.lower if lo is None else max(lo, p.lower)
                if p.upper is not None:
                    hi = p.upper if hi is None else min(hi, p.upper)

    walk(node)
    return lo, hi
