"""Coordinator: table/segment metadata, assignment, rebalance, retention.

Reference parity: PinotHelixResourceManager (pinot-controller/.../helix/core/
PinotHelixResourceManager.java — addTable :2045, addNewSegment :3037 ->
assignSegment :3056), assignment strategies (.../core/assignment/segment/),
TableRebalancer.rebalance (.../rebalance/TableRebalancer.java:201, contract
:122-134: never drop below min-available replicas), RetentionManager and
SegmentStatusChecker periodic tasks.

Re-design: ideal state / external view are dicts owned by this object (the
ZK-free control plane of SURVEY.md §2.6); servers register directly.  What
the reference persists to ZooKeeper persists here through an optional
durable metadata journal (cluster/journal.py: fsync'd JSONL + compacted
snapshots) — every mutation (table CRUD, segment assignment, replica-group
membership, rebalance commits, retention drops, realtime checkpoint
pointers) appends before it applies, so a coordinator built over the same
meta_dir after a crash rebuilds IDENTICAL ideal state, and re-registering
servers reconcile their local segment sets against it (re-downloading
missing/corrupt copies from the segment deep store, cluster/deepstore.py).
The routing view is versioned: every ideal-state or live-set transition
bumps `version`, so rebalance moves commit a new routing view instead of
mutating the one in-flight queries routed on.
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from pinot_tpu.cluster.election import FencedEpochError, NotLeaderError
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import Schema
from pinot_tpu.utils.crashpoints import crash_point

log = logging.getLogger("pinot_tpu.cluster")


def _jsonable(v: Any) -> Any:
    """Journal-safe JSON form: numpy scalars unwrap, tuples/sets become
    lists, bytes hex-tag themselves (restored by _unjsonable)."""
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted(_jsonable(x) for x in v)
    if isinstance(v, bytes):
        return {"__bytes__": v.hex()}
    return v


def _unjsonable(v: Any) -> Any:
    if isinstance(v, dict):
        if set(v) == {"__bytes__"}:
            return bytes.fromhex(v["__bytes__"])
        return {k: _unjsonable(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_unjsonable(x) for x in v]
    return v


def _restore_seg_meta(sm: Dict[str, Any]) -> Dict[str, Any]:
    """Segment metadata back from its journaled JSON form: the fields the
    broker pruners index positionally come back as tuples."""
    sm = dict(_unjsonable(sm))
    if sm.get("timeRange") is not None:
        sm["timeRange"] = tuple(sm["timeRange"])
    if sm.get("partition") is not None:
        sm["partition"] = tuple(sm["partition"])
    return sm


@dataclass
class TableMeta:
    schema: Schema
    config: TableConfig
    # ideal state: segment name -> set of server names that SHOULD serve it
    ideal: Dict[str, Set[str]] = field(default_factory=dict)
    # segment metadata the broker prunes on (time range, partition, docs)
    segment_meta: Dict[str, Dict] = field(default_factory=dict)


class Coordinator:
    def __init__(
        self,
        replication: int = 1,
        meta_dir: Optional[str] = None,
        deep_store=None,
        node_id: Optional[str] = None,
        standby: bool = False,
        lease_ttl_s: Optional[float] = None,
        clock=None,
    ):
        """`meta_dir` enables the durable control plane: mutations journal
        to {meta_dir}/journal.jsonl (+ compacted snapshots) and a fresh
        Coordinator over the same directory restores identical state.
        `deep_store` (a SegmentDeepStore or root path) is the durable
        segment home servers re-download from after a crash.

        Coordinator HA (round 18): a durable `meta_dir` also carries the
        leadership lease (cluster/election.py).  A non-standby boot FORCE
        acquires it — the operator restarting a coordinator over its own
        directory takes over, and the epoch bump fences any zombie of the
        previous process.  `standby=True` boots a HOT STANDBY instead: it
        tails the leader's journal incrementally (never writing the
        directory) and `promote()` — or `run_election_tick()` once the
        lease expires — makes it the fenced leader.  `clock`/`lease_ttl_s`
        parameterize the lease for tests and the bench (injectable clock;
        wall-clock lease math is a W022 lint error)."""
        self.replication = replication
        self.tables: Dict[str, TableMeta] = {}
        self.servers: Dict[str, "ServerInstance"] = {}  # noqa: F821
        self.live: Set[str] = set()
        # REALTIME tables: name -> RealtimeTableDataManager (coordinator-
        # owned consuming lifecycle; see add_realtime_table)
        self.realtime: Dict[str, object] = {}
        # replica-group membership: server -> group id (round-robin on join)
        self.replica_group: Dict[str, int] = {}
        self.num_replica_groups = max(1, replication)
        # group assignment reads len(replica_group) then writes it: two
        # servers joining concurrently would land in the same group
        self._membership_lock = threading.Lock()
        # live-set transition listeners: fn(server_name, is_up) — brokers
        # subscribe so circuit-breaker state resets when a server recovers
        self._live_listeners: List[Any] = []
        # versioned routing view: bumps on every ideal-state / live-set
        # mutation, so rebalance commits a NEW view instead of mutating the
        # one concurrent queries routed on
        self.version = 0
        # realtime table data dirs (journaled so a restored coordinator can
        # recover_realtime without the caller re-stating them)
        self._rt_dirs: Dict[str, str] = {}
        # last journaled realtime checkpoint pointer per (table, partition)
        self.rt_checkpoints: Dict[str, Dict[int, Dict[str, int]]] = {}
        if deep_store is not None and not hasattr(deep_store, "has_segment"):
            from pinot_tpu.cluster.deepstore import SegmentDeepStore

            deep_store = SegmentDeepStore(str(deep_store))
        self.deep_store = deep_store
        self.journal = None
        self.node_id = node_id or "coordinator"
        # a coordinator without a durable control plane is trivially the
        # leader of its single-process cluster
        self.role = "leader"
        self.election = None
        self._follower = None
        self._paused = False  # sim harness: a GC-frozen process serves nothing
        self.fault_plan = None  # set by FaultPlan.attach_coordinator
        if standby and meta_dir is None:
            raise ValueError("a standby coordinator requires meta_dir (it tails the leader's journal)")
        if meta_dir is not None:
            from pinot_tpu.cluster.election import JournalFollower, LeaseManager
            from pinot_tpu.cluster.journal import MetaJournal

            self.election = LeaseManager(
                meta_dir, self.node_id, ttl_s=lease_ttl_s, clock=clock
            )
            if standby:
                self.role = "standby"
                self._follower = JournalFollower(meta_dir)
                state = self._follower.bootstrap()
                if state:
                    self._apply_state(state)
                self.catch_up()
            else:
                # boot-time takeover: sweep crash leftovers (a stale
                # lease.json.tmp must never look like a live lease), then
                # force-acquire — the epoch bump fences any zombie writer
                self.election.sweep_stale_tmp()
                self.election.try_acquire(force=True)
                self.journal = MetaJournal(meta_dir)
                self.journal.fence = self.election
                if not self._restore():
                    # fresh journal: pin the cluster-wide invariants so a
                    # restored coordinator doesn't fall back to ctor defaults
                    self._journal(
                        "init",
                        replication=self.replication,
                        numReplicaGroups=self.num_replica_groups,
                    )

    # -- durable control plane -------------------------------------------
    def _journal(self, op: str, **data: Any) -> None:
        if self.journal is None:
            return
        try:
            self.journal.append(op, **data)
        except FencedEpochError:
            # the epoch fence tripped: leadership moved past us while we
            # thought we held it.  A deposed leader CANNOT commit — demote
            # to standby (the handle re-resolves) and surface the
            # structured error to the caller's retry path
            self._demote(release_lease=False)
            raise
        if self.journal.should_compact():
            self.journal.snapshot(self._state_dict())

    def _bump_version(self) -> None:
        with self._membership_lock:
            self.version += 1

    def _state_dict(self) -> Dict[str, Any]:
        """Full snapshot-able control-plane state (everything a restarted
        coordinator needs to rebuild identical ideal state)."""
        with self._membership_lock:
            groups = dict(self.replica_group)
        tables = {}
        for name, meta in self.tables.items():
            tables[name] = {
                "schema": meta.schema.to_dict(),
                "config": meta.config.to_dict(),
                "ideal": {seg: sorted(srvs) for seg, srvs in meta.ideal.items()},
                "segmentMeta": _jsonable(meta.segment_meta),
                "realtimeDataDir": self._rt_dirs.get(name),
            }
        return {
            "replication": self.replication,
            "numReplicaGroups": self.num_replica_groups,
            "tables": tables,
            "replicaGroup": groups,
            "rtCheckpoints": _jsonable(self.rt_checkpoints),
        }

    def _restore(self) -> bool:
        """Rebuild control-plane state from snapshot + journal replay.
        Servers are NOT live afterwards — they re-register and reconcile.
        Returns whether any durable state existed."""
        state, entries = self.journal.load()
        if state:
            self._apply_state(state)
        for entry in entries:
            self._apply_entry(entry)
        if state or entries:
            self._bump_version()
            return True
        return False

    def _apply_state(self, state: Dict[str, Any]) -> None:
        self.replication = int(state.get("replication", self.replication))
        self.num_replica_groups = int(state.get("numReplicaGroups", self.num_replica_groups))
        with self._membership_lock:
            self.replica_group = {
                str(k): int(v) for k, v in (state.get("replicaGroup") or {}).items()
            }
        for name, t in (state.get("tables") or {}).items():
            meta = TableMeta(
                schema=Schema.from_dict(t["schema"]),
                config=TableConfig.from_dict(t["config"]),
            )
            meta.ideal = {seg: set(srvs) for seg, srvs in (t.get("ideal") or {}).items()}
            meta.segment_meta = {
                seg: _restore_seg_meta(sm) for seg, sm in (t.get("segmentMeta") or {}).items()
            }
            self.tables[name] = meta
            if t.get("realtimeDataDir"):
                self._rt_dirs[name] = t["realtimeDataDir"]
        for table, parts in (state.get("rtCheckpoints") or {}).items():
            self.rt_checkpoints[table] = {
                int(p): dict(cp) for p, cp in (parts or {}).items()
            }

    def _apply_entry(self, entry: Dict[str, Any]) -> None:
        """Replay one journal entry.  Every op is idempotent (set-valued
        ideal state, last-writer pointers) so the snapshot/journal overlap a
        crash mid-compaction produces re-applies harmlessly."""
        op = entry.get("op")
        if op == "init":
            self.replication = int(entry.get("replication", self.replication))
            self.num_replica_groups = int(
                entry.get("numReplicaGroups", self.num_replica_groups)
            )
        elif op == "add_table":
            name = entry["table"]
            if name not in self.tables:
                self.tables[name] = TableMeta(
                    schema=Schema.from_dict(entry["schema"]),
                    config=TableConfig.from_dict(entry["config"]),
                )
            if entry.get("realtimeDataDir"):
                self._rt_dirs[name] = entry["realtimeDataDir"]
        elif op == "drop_table":
            self.tables.pop(entry["table"], None)
            self._rt_dirs.pop(entry["table"], None)
            self.rt_checkpoints.pop(entry["table"], None)
        elif op == "set_ideal":
            meta = self.tables.get(entry["table"])
            if meta is not None:
                meta.ideal[entry["segment"]] = set(entry["servers"])
                if entry.get("meta") is not None:
                    meta.segment_meta[entry["segment"]] = _restore_seg_meta(entry["meta"])
        elif op == "drop_segment":
            meta = self.tables.get(entry["table"])
            if meta is not None:
                meta.ideal.pop(entry["segment"], None)
                meta.segment_meta.pop(entry["segment"], None)
        elif op == "register_server":
            with self._membership_lock:
                self.replica_group[entry["server"]] = int(entry["group"])
        elif op == "rt_checkpoint":
            self.rt_checkpoints.setdefault(entry["table"], {})[int(entry["partition"])] = {
                "offset": int(entry["offset"]),
                "seq": int(entry["segSeq"]),
            }
        else:  # forward-compat: unknown ops are recorded, not fatal
            log.warning("unknown journal op %r (seq %s) ignored", op, entry.get("seq"))

    def checkpoint_metadata(self) -> None:
        """Force a compacted snapshot now (periodic-task / shutdown hook)."""
        if self.journal is not None:
            self.journal.snapshot(self._state_dict())

    # -- leadership (lease-based election, cluster/election.py) -----------
    def _require_leader(self) -> None:
        """Gate on every control-plane mutation: standbys (and paused
        processes) refuse with the structured error CoordinatorHandle
        retries on.  This is the cheap in-memory check — the EPOCH FENCE in
        the journal is the authority for durable writes (a stale leader's
        non-journaled op may briefly succeed here, exactly like the
        reference's external-view lag; anything durable cannot)."""
        if self._paused:
            raise NotLeaderError(f"coordinator {self.node_id} is paused (frozen process)")
        if self.role != "leader":
            raise NotLeaderError(
                f"coordinator {self.node_id} is a standby (control-plane "
                "writes go to the leader)",
            )

    def pause(self) -> None:
        """Simulation harness: freeze this process (GC pause / VM stall).
        Every control-plane entry point refuses while paused; lease
        renewals silently stop (the FaultPlan leader_pause rule drives
        this).  Data-plane reads stay up — brokers ride the last versioned
        routing view, which this object still holds."""
        self._paused = True

    def resume(self) -> None:
        """Unfreeze.  The process still believes it leads (role unchanged);
        if the lease moved on while frozen, its next journal append trips
        the epoch fence and demotes it — the split-brain proof."""
        self._paused = False

    def catch_up(self) -> int:
        """Standby: apply newly committed journal entries (incremental tail
        over the shared TailFollower).  Returns entries applied."""
        if self._follower is None:
            return 0
        state, entries = self._follower.poll()
        if state is not None:
            # the leader compacted under us: resync from its snapshot
            self._reset_state()
            self._apply_state(state)
        for entry in entries:
            self._apply_entry(entry)
        if state is not None or entries:
            self._bump_version()
            from pinot_tpu.utils.metrics import METRICS

            METRICS.counter("coordinator.standbyEntriesApplied").inc(len(entries))
        return len(entries)

    def _reset_state(self) -> None:
        """Drop replayable control-plane state before a snapshot resync
        (membership/live/listeners survive — they are runtime, not
        journaled, state)."""
        self.tables.clear()
        self._rt_dirs.clear()
        self.rt_checkpoints.clear()
        with self._membership_lock:
            self.replica_group.clear()

    def promote(self, force: bool = False) -> bool:
        """Standby -> leader: acquire the lease (bumping the epoch — the
        fencing token every subsequent append carries), replay the journal
        to tip, attach the fence, serve.  Polite by default: returns False
        while the current lease is live (set `force` for an operator
        override).  Idempotent on an already-leading coordinator."""
        if self.role == "leader":
            return True
        if self.election is None or self._follower is None:
            raise RuntimeError("promote() needs a durable meta_dir standby")
        from pinot_tpu.cluster.journal import MetaJournal
        from pinot_tpu.utils.metrics import METRICS

        t0 = time.perf_counter()
        self.catch_up()  # drain what the old leader committed
        if not self.election.try_acquire(force=force):
            return False
        crash_point("election.promote.after_acquire")
        # now the directory is OURS: sweep crash leftovers and drain
        # anything that fsync'd between the first drain and the acquisition
        self.election.sweep_stale_tmp()
        self.catch_up()
        # become the journal's writer: adopt the committed seq (load also
        # truncates a torn tail so our appends start on a clean line)
        journal = MetaJournal(self.election.meta_dir)
        journal.fence = self.election
        journal.fault_plan = self.fault_plan
        _state, _entries = journal.load()
        if self._follower.last_seq != journal.seq:
            # the incremental tail diverged from an authoritative load
            # (quarantined corruption it skipped past): full resync
            METRICS.counter("coordinator.promoteResyncs").inc()
            log.warning(
                "standby %s tail (seq %d) != journal tip (seq %d); full replay",
                self.node_id, self._follower.last_seq, journal.seq,
            )
            self._reset_state()
            if _state:
                self._apply_state(_state)
            for entry in _entries:
                self._apply_entry(entry)
        self.journal = journal
        self._follower = None
        self.role = "leader"
        self._bump_version()
        self.last_promote_ms = (time.perf_counter() - t0) * 1000.0
        METRICS.counter("coordinator.failovers").inc()
        METRICS.gauge("coordinator.isLeader").set(1)
        log.warning(
            "coordinator %s promoted to leader at epoch %d (replay-to-tip %.1f ms)",
            self.node_id, self.election.epoch, self.last_promote_ms,
        )
        return True

    def _demote(self, release_lease: bool) -> None:
        """Leader -> standby.  `release_lease` distinguishes a voluntary
        step-down (expire the lease now so a standby takes over instantly)
        from being DEPOSED (the lease belongs to the new leader — touching
        it would be exactly the zombie write the fence exists to stop)."""
        from pinot_tpu.cluster.election import JournalFollower
        from pinot_tpu.utils.metrics import METRICS

        if self.role != "leader" or self.election is None:
            return
        seq = 0
        if self.journal is not None:
            seq = self.journal.seq
            self.journal.close()
            self.journal = None
        if release_lease:
            self.election.release()
        else:
            self.election.is_leader = False
        follower = JournalFollower(self.election.meta_dir)
        # our in-memory state matches the committed prefix (journal-before-
        # apply, and the fence refuses before any byte lands): tail from it
        follower.last_seq = seq
        follower.max_epoch = self.election.epoch
        self._follower = follower
        self.role = "standby"
        METRICS.gauge("coordinator.isLeader").set(0)
        log.warning("coordinator %s demoted to standby (epoch %d)", self.node_id, self.election.epoch)

    def demote(self) -> None:
        """Voluntary step-down (operator drain): release the lease so a
        standby can take over without waiting out the TTL."""
        self._demote(release_lease=True)

    def run_election_tick(self) -> str:
        """One deterministic step of the leadership watch loop (tests, the
        bench, and CoordinatorHandle's failover park drive this; a real
        deployment would run it on a timer thread): leaders renew their
        lease (demoting when deposed), standbys tail the journal and take
        over an expired lease.  Returns the role after the tick."""
        if self.election is None or self._paused:
            return self.role
        if self.role == "leader":
            if not self.election.renew():
                self._demote(release_lease=False)
        else:
            self.catch_up()
            cur = self.election.read()
            # take over an expired lease — or finish our OWN half-done
            # acquisition (a crash between lease acquire and journal
            # adoption leaves the lease held but the role standby)
            if self.election.expired() or (
                cur is not None and cur.holder == self.election.node_id
            ):
                self.promote()
        return self.role

    def election_state(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"node": self.node_id, "role": self.role, "paused": self._paused}
        if self.election is not None:
            out.update(self.election.snapshot())
        journal = self.journal
        if journal is not None:
            out["journalSeq"] = journal.seq
        elif self._follower is not None:
            out["journalSeq"] = self._follower.last_seq
        return out

    def election_snapshot(self) -> Dict[str, Any]:
        """Single-coordinator form of CoordinatorHandle.election_snapshot
        (REST /debug/election works against either)."""
        return {
            "leader": self.node_id if self.role == "leader" else None,
            "candidates": [self.election_state()],
        }

    def on_live_change(self, fn) -> None:
        self._live_listeners.append(fn)

    def _notify_live(self, name: str, up: bool) -> None:
        from pinot_tpu.utils.metrics import METRICS

        for fn in list(self._live_listeners):
            try:
                fn(name, up)
            except Exception:  # noqa: BLE001 — one bad listener must not block transitions
                METRICS.counter("liveListenerErrors").inc()
                log.exception("live-set listener failed for %s", name)

    # -- instance lifecycle (Helix participant analog) -------------------
    def register_server(self, server) -> None:
        self._require_leader()
        # attach the per-server HBM reservation ledger (admission tentpole):
        # scatter calls reserve their working-set estimate against it before
        # launching, so concurrent queries can't jointly overcommit HBM.
        # Constructed outside the membership lock (it publishes a gauge).
        if getattr(server, "budget", None) is None:
            from pinot_tpu.cluster.admission import ResourceBudget, default_server_hbm_budget

            hbm = default_server_hbm_budget()
            if hbm > 0:
                server.budget = ResourceBudget(
                    hbm, gauge=f"server.reservedBytes.{server.name}"
                )
        # attach the tiered-storage residency manager (r17 tentpole): HBM
        # becomes a cost-aware cache over the segments' host arrays, so
        # ASSIGNMENT NO LONGER ASSUMES FULL PINNING — a server can own a
        # working set larger than device memory and page it through the
        # cache budget with staged prefetch.  Its cache ledger is a
        # SEPARATE ResourceBudget from server.budget: reservations meter
        # in-flight scatter windows, the residency budget meters resident
        # cached bytes (PINOT_TPU_HBM_CACHE_BYTES=0 disables tiering).
        if getattr(server, "residency", None) is None:
            from pinot_tpu.segment.residency import default_residency

            server.residency = default_residency(name=f"residency.{server.name}")
        with self._membership_lock:
            self.servers[server.name] = server
            self.live.add(server.name)
            known = server.name in self.replica_group
            if not known:
                self.replica_group[server.name] = len(self.replica_group) % self.num_replica_groups
            group = self.replica_group[server.name]
            self.version += 1
        if not known:
            # membership is durable state: a restored coordinator must place
            # segments into the same replica groups it journaled
            self._journal("register_server", server=server.name, group=group)
        # restart recovery: a (re-)registering server reconciles its local
        # segment set against the journaled ideal state — re-downloading
        # missing/corrupt copies from the deep store, dropping stale ones
        self.reconcile_server(server)
        self._notify_live(server.name, up=True)

    def reconcile_server(self, server) -> Dict[str, int]:
        """Bring one server's local segment set in line with ideal state
        (the Helix state-transition batch a re-joining participant runs).
        Missing segments restore from the deep store (CRC-verified) or a
        live peer's copy; segments ideal no longer assigns here drop."""
        from pinot_tpu.utils.metrics import METRICS

        restored = dropped = missing = 0
        with self._membership_lock:
            live = set(self.live)
        for table, meta in self.tables.items():
            want = {seg for seg, srvs in meta.ideal.items() if server.name in srvs}
            have = set(server.segment_names(table))
            for seg_name in sorted(have - want):
                server.drop_segment(table, seg_name)
                dropped += 1
            for seg_name in sorted(want - have):
                seg = None
                if self.deep_store is not None and self.deep_store.has_segment(table, seg_name):
                    try:
                        seg = server.restore_segment(table, seg_name, self.deep_store)
                    except Exception:  # noqa: BLE001 — fall through to a peer copy
                        METRICS.counter("coordinator.restoreFailures").inc()
                        log.exception(
                            "deep-store restore of %s/%s onto %s failed",
                            table, seg_name, server.name,
                        )
                if seg is None:
                    obj = self._find_segment_object(
                        table, seg_name, (meta.ideal.get(seg_name, set()) | live) - {server.name}
                    )
                    if obj is not None:
                        server.add_segment(table, obj)
                        seg = obj
                if seg is not None:
                    restored += 1
                else:
                    missing += 1
                    METRICS.counter("coordinator.segmentsUnrecoverable").inc()
                    log.error(
                        "segment %s/%s assigned to %s is in neither the deep store "
                        "nor any live replica", table, seg_name, server.name,
                    )
        if restored or dropped:
            METRICS.counter("coordinator.segmentsRestored").inc(restored)
            self._bump_version()
        return {"restored": restored, "dropped": dropped, "missing": missing}

    def mark_down(self, name: str) -> None:
        """Liveness loss (Helix session expiry analog): external view drops
        the server; ideal state keeps it until rebalance repairs."""
        self._require_leader()
        with self._membership_lock:
            was_live = name in self.live
            self.live.discard(name)
            if was_live:
                self.version += 1
        if was_live:
            # listeners run outside the lock: they take their own locks
            # (broker breaker reset) and must not order against membership
            self._notify_live(name, up=False)

    def mark_up(self, name: str) -> None:
        self._require_leader()
        with self._membership_lock:
            recovered = name in self.servers and name not in self.live
            if recovered:
                self.live.add(name)
                self.version += 1
        if recovered:
            self._notify_live(name, up=True)

    # -- server crash / restart (process-death simulation harness) --------
    def crash_server(self, name: str) -> None:
        """Kill a server: its in-memory/HBM segment state is LOST (the
        process died), and the external view drops it."""
        with self._membership_lock:
            server = self.servers.get(name)
        if server is not None:
            server.crash()
        self.mark_down(name)

    def restart_server(self, name: str) -> Dict[str, int]:
        """Restart a crashed server: reconcile its (empty) local state
        against ideal state — re-download committed segments from the deep
        store, re-pin to device — then rejoin the live set, which heals
        broker routing/breakers via the mark_up listener path."""
        with self._membership_lock:
            server = self.servers[name]
        server.boot()
        stats = self.reconcile_server(server)
        self.mark_up(name)
        return stats

    # -- table CRUD ------------------------------------------------------
    def add_table(self, schema: Schema, config: Optional[TableConfig] = None) -> None:
        self._require_leader()
        cfg = config or TableConfig(name=schema.name)
        if cfg.name in self.tables:
            raise ValueError(f"table {cfg.name} already exists")
        self._journal("add_table", table=cfg.name, schema=schema.to_dict(), config=cfg.to_dict())
        self.tables[cfg.name] = TableMeta(schema=schema, config=cfg)
        self._bump_version()

    def add_realtime_table(self, schema: Schema, config: TableConfig, data_dir: str, stream=None):
        """Create a REALTIME table owned by the cluster: the coordinator
        holds its RealtimeTableDataManager (the PinotLLCRealtimeSegmentManager
        slot — consuming-segment lifecycle lives here, not on a server) and
        the broker serves sealed + consuming segments from it."""
        from pinot_tpu.realtime import RealtimeTableDataManager

        self._require_leader()
        if config.name in self.tables:
            raise ValueError(f"table {config.name} already exists")
        self._journal(
            "add_table",
            table=config.name,
            schema=schema.to_dict(),
            config=config.to_dict(),
            realtimeDataDir=data_dir,
        )
        self.tables[config.name] = TableMeta(schema=schema, config=config)
        self._rt_dirs[config.name] = data_dir
        self._bump_version()
        mgr = RealtimeTableDataManager(
            schema, config, data_dir, stream=stream, deep_store=self.deep_store
        )
        self._attach_realtime(config.name, mgr)
        return mgr

    def _attach_realtime(self, name: str, mgr) -> None:
        self.realtime[name] = mgr

        # checkpoint pointers are control-plane state: journal each commit
        # so a restored coordinator knows the committed (offset, seq) per
        # partition without touching the table's data dir
        def _on_checkpoint(partition: int, offset: int, seq: int, _t=name) -> None:
            self.rt_checkpoints.setdefault(_t, {})[int(partition)] = {
                "offset": int(offset), "seq": int(seq),
            }
            # "segSeq", not "seq": the journal reserves "seq" for its own
            # append ordering
            self._journal("rt_checkpoint", table=_t, partition=partition, offset=offset, segSeq=seq)

        mgr.on_checkpoint = _on_checkpoint

    def recover_realtime(self, name: str, stream=None):
        """Re-create a journaled realtime table's manager after coordinator
        restart.  The manager replays its own fsync'd checkpoint (sealed
        segments + committed offsets); `stream` re-binds the live source
        (memory streams can't be journaled — file/kafka-style configs
        rebuild from TableConfig alone)."""
        from pinot_tpu.realtime import RealtimeTableDataManager

        if name in self.realtime:
            return self.realtime[name]
        meta = self.tables[name]
        data_dir = self._rt_dirs.get(name)
        if data_dir is None:
            raise KeyError(f"table {name!r} was not journaled as a realtime table")
        mgr = RealtimeTableDataManager(
            meta.schema, meta.config, data_dir, stream=stream, deep_store=self.deep_store
        )
        self._attach_realtime(name, mgr)
        self._bump_version()
        return mgr

    def run_realtime_consumption(self, max_batches: Optional[int] = None) -> int:
        """Step every realtime table's consume loops (the periodic driver the
        reference runs as per-partition consumer threads)."""
        total = 0
        for mgr in getattr(self, "realtime", {}).values():
            total += mgr.consume_all(max_batches=max_batches)
        return total

    def drop_table(self, name: str) -> None:
        self._require_leader()
        self._journal("drop_table", table=name)
        meta = self.tables.pop(name)
        self.realtime.pop(name, None)
        self._rt_dirs.pop(name, None)
        self._bump_version()
        with self._membership_lock:
            servers = dict(self.servers)
        for seg_name, assigned in meta.ideal.items():
            for s in assigned:
                if s in servers:
                    servers[s].drop_segment(name, seg_name)

    # -- segment registration + assignment -------------------------------
    def add_segment(self, table: str, segment: ImmutableSegment) -> List[str]:
        """addNewSegment -> assignSegment -> server state transitions.

        Durability ordering: segment data reaches the deep store FIRST,
        then the assignment journals, then servers load — a crash at any
        point leaves metadata that only ever references durable data, and
        restart reconciliation completes the placement."""
        self._require_leader()
        meta = self.tables[table]
        targets = self._assign(meta, segment.name)
        if self.deep_store is not None:
            self.deep_store.put_segment(table, segment)
        crash_point("coordinator.add_segment.after_upload")
        seg_meta = self._seg_meta(segment)
        self._journal(
            "set_ideal",
            table=table,
            segment=segment.name,
            servers=sorted(targets),
            meta=_jsonable(seg_meta),
        )
        crash_point("coordinator.add_segment.after_journal")
        meta.ideal[segment.name] = set(targets)
        meta.segment_meta[segment.name] = seg_meta
        self._bump_version()
        with self._membership_lock:
            servers = {s: self.servers[s] for s in targets}
        for s in targets:
            # device placement (HBM pins) happens outside the lock
            servers[s].add_segment(table, segment)
        return targets

    def _set_ideal(self, table: str, seg_name: str, servers: Set[str]) -> None:
        """Journal + apply one segment's new assignment (rebalance commit)."""
        self._journal("set_ideal", table=table, segment=seg_name, servers=sorted(servers))
        self.tables[table].ideal[seg_name] = set(servers)
        self._bump_version()

    def _seg_meta(self, segment: ImmutableSegment) -> Dict:
        part = None
        for c in segment.columns.values():
            if c.stats.partition_id is not None:
                part = (c.name, c.stats.partition_id, c.stats.num_partitions)
        # per-column stats for broker-side range injection (ZK segment
        # metadata analog: the broker never touches segment data)
        col_stats = {}
        for c in segment.columns.values():
            col_stats[c.name] = {
                "min": c.stats.min_value,
                "max": c.stats.max_value,
                "dictFp": c.dictionary.fingerprint() if c.has_dictionary else None,
            }
        from pinot_tpu.cluster.server import _segment_bytes

        return {
            "numDocs": segment.num_docs,
            "timeRange": segment.time_range,
            "partition": part,
            "creationTimeMs": segment.creation_time_ms,
            "colStats": col_stats,
            # host-array residency: the broker's per-query cost estimator
            # sizes HBM working sets from this without touching segment data
            "bytes": _segment_bytes(segment),
        }

    def _assign(self, meta: TableMeta, seg_name: str) -> List[str]:
        """Replica-group aware balanced placement: one server per replica
        group (replication R = R groups), least-loaded within the group."""
        with self._membership_lock:
            live = set(self.live)
            groups = dict(self.replica_group)
        if not live:
            raise RuntimeError("no live servers to assign to")
        loads = {s: 0 for s in live}
        for segs in meta.ideal.values():
            for s in segs:
                if s in loads:
                    loads[s] += 1
        out: List[str] = []
        for g in range(self.num_replica_groups):
            members = [s for s in live if groups.get(s) == g]
            if not members:
                continue
            out.append(min(members, key=lambda s: (loads[s], s)))
        # a replica group with zero live members can't host its copy — top up
        # replication from the remaining live servers (availability over
        # strict group placement, like the reference's non-strict fallback)
        want = min(self.replication, len(live))
        remaining = [s for s in live if s not in out]
        while len(out) < want and remaining:
            pick = min(remaining, key=lambda s: (loads[s], s))
            remaining.remove(pick)
            out.append(pick)
        return out

    def mesh_placement(self, num_replica_rows: int) -> Dict[int, List[str]]:
        """Map the 2-D mesh's replica rows onto live servers: row r serves
        the replica groups congruent to r (mod num_replica_rows).  A derived
        view over replica_group/live — it tracks rebalances and failovers
        automatically, and CoordinatorHandle makes it HA-aware like every
        other Coordinator method.  An engine-side ReplicatedEngine consults
        this to skip rows whose backing servers are all dead."""
        rows = max(1, int(num_replica_rows))
        with self._membership_lock:
            live = set(self.live)
            groups = dict(self.replica_group)
        out: Dict[int, List[str]] = {r: [] for r in range(rows)}
        for server in sorted(live):
            out[groups.get(server, 0) % rows].append(server)
        return out

    # -- views -----------------------------------------------------------
    def external_view(self, table: str) -> Dict[str, Set[str]]:
        """Ideal state filtered to LIVE servers — what the broker routes on
        (ExternalView analog)."""
        meta = self.tables[table]
        with self._membership_lock:
            live = set(self.live)
        return {seg: {s for s in servers if s in live} for seg, servers in meta.ideal.items()}

    def versioned_view(self, table: str) -> Tuple[int, Dict[str, Set[str]]]:
        """(version, external view) — the version identifies which routing
        epoch a query's snapshot came from; rebalance/liveness transitions
        bump it, so two different answers are never attributed to one view."""
        meta = self.tables[table]
        with self._membership_lock:
            live = set(self.live)
            version = self.version
        view = {seg: {s for s in servers if s in live} for seg, servers in meta.ideal.items()}
        return version, view

    # -- rebalance --------------------------------------------------------
    def rebalance(self, table: str, min_available_replicas: int = 1) -> Dict[str, int]:
        """Live rebalance over the CURRENT live set (TableRebalancer.java
        :122-134 contract: load-before-drop, never below the availability
        floor, each move committed to the journal before old copies drop)."""
        from pinot_tpu.cluster.rebalance import TableRebalancer

        self._require_leader()
        return TableRebalancer(self).rebalance(
            table, min_available_replicas=min_available_replicas
        )

    def _assign_for_rebalance(self, meta: TableMeta, seg_name: str) -> List[str]:
        return self._assign(meta, seg_name)

    def _find_segment_object(self, table: str, seg_name: str, candidates) -> Optional[ImmutableSegment]:
        with self._membership_lock:
            live = set(self.live)
            servers = dict(self.servers)
        for s in candidates:
            if s in live and s in servers:
                seg = servers[s].get_segment(table, seg_name)
                if seg is not None:
                    return seg
        return None

    # -- periodic tasks ---------------------------------------------------
    def run_retention(self, now_ms: Optional[int] = None) -> List[str]:
        """RetentionManager: drop segments whose time range fell out of the
        retention window."""
        self._require_leader()
        now_ms = now_ms or int(time.time() * 1000)
        with self._membership_lock:
            servers = dict(self.servers)
        purged: List[str] = []
        unit_ms = {"DAYS": 86_400_000, "HOURS": 3_600_000, "MINUTES": 60_000}
        for table, meta in self.tables.items():
            sc = meta.config.segments
            if sc.retention_time_value is None:
                continue
            horizon = now_ms - sc.retention_time_value * unit_ms.get(sc.retention_time_unit, 86_400_000)
            for seg_name in list(meta.ideal):
                tr = meta.segment_meta.get(seg_name, {}).get("timeRange")
                if tr is not None and tr[1] is not None and tr[1] < horizon:
                    self._journal("drop_segment", table=table, segment=seg_name)
                    for s in meta.ideal.pop(seg_name):
                        if s in servers:
                            servers[s].drop_segment(table, seg_name)
                    meta.segment_meta.pop(seg_name, None)
                    self._bump_version()
                    purged.append(f"{table}/{seg_name}")
        return purged

    # -- liveness (Helix session-expiry analog) ---------------------------
    def heartbeat(self, server_name: str) -> None:
        """Servers call this periodically; check_liveness marks stale ones
        down (the failure-DETECTION half of SURVEY §5.3 — rebalance is the
        recovery half).  Staleness is measured on the monotonic clock: an
        NTP step on the wall clock must never mass-expire the fleet."""
        self._require_leader()
        if not hasattr(self, "_heartbeats"):
            self._heartbeats: Dict[str, float] = {}
        self._heartbeats[server_name] = time.monotonic()
        # a recovered server resumes serving (Helix session re-establishment)
        with self._membership_lock:
            recovered = server_name in self.servers and server_name not in self.live
        if recovered:
            self.mark_up(server_name)

    def check_liveness(self, timeout_s: float = 30.0) -> List[str]:
        """Mark servers with stale heartbeats down; returns who was dropped."""
        now = time.monotonic()
        dropped = []
        with self._membership_lock:
            live = list(self.live)
        for name in live:
            hb = getattr(self, "_heartbeats", {}).get(name)
            if hb is not None and now - hb > timeout_s:
                self.mark_down(name)
                dropped.append(name)
        return dropped

    def run_periodic_tasks(self, heartbeat_timeout_s: float = 30.0) -> Dict[str, Any]:
        """One tick of the controller periodic-task set
        (ControllerPeriodicTask analog): liveness check, retention purge,
        realtime consumption step, auto-rebalance of tables with
        under-replicated segments, status report."""
        self._require_leader()
        dropped = self.check_liveness(heartbeat_timeout_s)
        purged = self.run_retention()
        consumed = self.run_realtime_consumption(max_batches=4)
        status = self.status_report()
        rebalanced = []
        with self._membership_lock:
            any_live = bool(self.live)
        for table, st in status.items():
            if st["underReplicated"] and any_live:
                self.rebalance(table)
                rebalanced.append(table)
        return {
            "serversDropped": dropped,
            "segmentsPurged": purged,
            "rowsConsumed": consumed,
            "tablesRebalanced": rebalanced,
        }

    def start_periodic_tasks(self, interval_s: float = 5.0, stop_event=None) -> "threading.Thread":
        """Background periodic-task thread (daemonized)."""
        from pinot_tpu.utils.metrics import METRICS

        def loop():
            while stop_event is None or not stop_event.is_set():
                try:
                    self.run_periodic_tasks()
                except Exception:  # noqa: BLE001 — periodic tasks must not die
                    METRICS.counter("periodicTaskExceptions").inc()
                    log.exception("periodic task tick failed")
                time.sleep(interval_s)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t

    def status_report(self) -> Dict[str, Dict]:
        """SegmentStatusChecker: per-table replica health."""
        with self._membership_lock:
            live = set(self.live)
            servers = dict(self.servers)
        # per-server HBM reservation occupancy (admission ledger view)
        reserved = {}
        residency = {}
        for name, srv in servers.items():
            budget = getattr(srv, "budget", None)
            if budget is not None:
                reserved[name] = budget.snapshot()
            res = getattr(srv, "residency", None)
            if res is not None:
                # tiered-storage cache view: resident bytes, hit/miss/
                # eviction/prefetch counters per server
                residency[name] = res.snapshot()
        out: Dict[str, Dict] = {}
        for table, meta in self.tables.items():
            under = []
            for seg, seg_servers in meta.ideal.items():
                n_live = sum(1 for s in seg_servers if s in live)
                if n_live < min(self.replication, len(seg_servers)) or n_live == 0:
                    under.append(seg)
            out[table] = {
                "segments": len(meta.ideal),
                "underReplicated": under,
                "liveServers": sorted(live),
                "reservedBytes": reserved,
                "residency": residency,
            }
        return out
