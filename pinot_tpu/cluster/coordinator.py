"""Coordinator: table/segment metadata, assignment, rebalance, retention.

Reference parity: PinotHelixResourceManager (pinot-controller/.../helix/core/
PinotHelixResourceManager.java — addTable :2045, addNewSegment :3037 ->
assignSegment :3056), assignment strategies (.../core/assignment/segment/),
TableRebalancer.rebalance (.../rebalance/TableRebalancer.java:201, contract
:122-134: never drop below min-available replicas), RetentionManager and
SegmentStatusChecker periodic tasks.

Re-design: ideal state / external view are plain dicts owned by this object
(the ZK-free control plane of SURVEY.md §2.6); servers register directly.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import Schema


@dataclass
class TableMeta:
    schema: Schema
    config: TableConfig
    # ideal state: segment name -> set of server names that SHOULD serve it
    ideal: Dict[str, Set[str]] = field(default_factory=dict)
    # segment metadata the broker prunes on (time range, partition, docs)
    segment_meta: Dict[str, Dict] = field(default_factory=dict)


class Coordinator:
    def __init__(self, replication: int = 1):
        self.replication = replication
        self.tables: Dict[str, TableMeta] = {}
        self.servers: Dict[str, "ServerInstance"] = {}  # noqa: F821
        self.live: Set[str] = set()
        # REALTIME tables: name -> RealtimeTableDataManager (coordinator-
        # owned consuming lifecycle; see add_realtime_table)
        self.realtime: Dict[str, object] = {}
        # replica-group membership: server -> group id (round-robin on join)
        self.replica_group: Dict[str, int] = {}
        self.num_replica_groups = max(1, replication)
        # group assignment reads len(replica_group) then writes it: two
        # servers joining concurrently would land in the same group
        self._membership_lock = threading.Lock()
        # live-set transition listeners: fn(server_name, is_up) — brokers
        # subscribe so circuit-breaker state resets when a server recovers
        self._live_listeners: List[Any] = []

    def on_live_change(self, fn) -> None:
        self._live_listeners.append(fn)

    def _notify_live(self, name: str, up: bool) -> None:
        import logging

        from pinot_tpu.utils.metrics import METRICS

        for fn in list(self._live_listeners):
            try:
                fn(name, up)
            except Exception:  # noqa: BLE001 — one bad listener must not block transitions
                METRICS.counter("liveListenerErrors").inc()
                logging.getLogger("pinot_tpu.cluster").exception(
                    "live-set listener failed for %s", name
                )

    # -- instance lifecycle (Helix participant analog) -------------------
    def register_server(self, server) -> None:
        # attach the per-server HBM reservation ledger (admission tentpole):
        # scatter calls reserve their working-set estimate against it before
        # launching, so concurrent queries can't jointly overcommit HBM.
        # Constructed outside the membership lock (it publishes a gauge).
        if getattr(server, "budget", None) is None:
            from pinot_tpu.cluster.admission import ResourceBudget, default_server_hbm_budget

            hbm = default_server_hbm_budget()
            if hbm > 0:
                server.budget = ResourceBudget(
                    hbm, gauge=f"server.reservedBytes.{server.name}"
                )
        with self._membership_lock:
            self.servers[server.name] = server
            self.live.add(server.name)
            self.replica_group[server.name] = len(self.replica_group) % self.num_replica_groups

    def mark_down(self, name: str) -> None:
        """Liveness loss (Helix session expiry analog): external view drops
        the server; ideal state keeps it until rebalance repairs."""
        with self._membership_lock:
            was_live = name in self.live
            self.live.discard(name)
        if was_live:
            # listeners run outside the lock: they take their own locks
            # (broker breaker reset) and must not order against membership
            self._notify_live(name, up=False)

    def mark_up(self, name: str) -> None:
        with self._membership_lock:
            recovered = name in self.servers and name not in self.live
            if recovered:
                self.live.add(name)
        if recovered:
            self._notify_live(name, up=True)

    # -- table CRUD ------------------------------------------------------
    def add_table(self, schema: Schema, config: Optional[TableConfig] = None) -> None:
        cfg = config or TableConfig(name=schema.name)
        if cfg.name in self.tables:
            raise ValueError(f"table {cfg.name} already exists")
        self.tables[cfg.name] = TableMeta(schema=schema, config=cfg)

    def add_realtime_table(self, schema: Schema, config: TableConfig, data_dir: str, stream=None):
        """Create a REALTIME table owned by the cluster: the coordinator
        holds its RealtimeTableDataManager (the PinotLLCRealtimeSegmentManager
        slot — consuming-segment lifecycle lives here, not on a server) and
        the broker serves sealed + consuming segments from it."""
        from pinot_tpu.realtime import RealtimeTableDataManager

        self.add_table(schema, config)
        mgr = RealtimeTableDataManager(schema, config, data_dir, stream=stream)
        self.realtime[config.name] = mgr
        return mgr

    def run_realtime_consumption(self, max_batches: Optional[int] = None) -> int:
        """Step every realtime table's consume loops (the periodic driver the
        reference runs as per-partition consumer threads)."""
        total = 0
        for mgr in getattr(self, "realtime", {}).values():
            total += mgr.consume_all(max_batches=max_batches)
        return total

    def drop_table(self, name: str) -> None:
        meta = self.tables.pop(name)
        with self._membership_lock:
            servers = dict(self.servers)
        for seg_name, assigned in meta.ideal.items():
            for s in assigned:
                if s in servers:
                    servers[s].drop_segment(name, seg_name)

    # -- segment registration + assignment -------------------------------
    def add_segment(self, table: str, segment: ImmutableSegment) -> List[str]:
        """addNewSegment -> assignSegment -> server state transitions."""
        meta = self.tables[table]
        targets = self._assign(meta, segment.name)
        meta.ideal[segment.name] = set(targets)
        meta.segment_meta[segment.name] = self._seg_meta(segment)
        with self._membership_lock:
            servers = {s: self.servers[s] for s in targets}
        for s in targets:
            # device placement (HBM pins) happens outside the lock
            servers[s].add_segment(table, segment)
        return targets

    def _seg_meta(self, segment: ImmutableSegment) -> Dict:
        part = None
        for c in segment.columns.values():
            if c.stats.partition_id is not None:
                part = (c.name, c.stats.partition_id, c.stats.num_partitions)
        # per-column stats for broker-side range injection (ZK segment
        # metadata analog: the broker never touches segment data)
        col_stats = {}
        for c in segment.columns.values():
            col_stats[c.name] = {
                "min": c.stats.min_value,
                "max": c.stats.max_value,
                "dictFp": c.dictionary.fingerprint() if c.has_dictionary else None,
            }
        from pinot_tpu.cluster.server import _segment_bytes

        return {
            "numDocs": segment.num_docs,
            "timeRange": segment.time_range,
            "partition": part,
            "creationTimeMs": segment.creation_time_ms,
            "colStats": col_stats,
            # host-array residency: the broker's per-query cost estimator
            # sizes HBM working sets from this without touching segment data
            "bytes": _segment_bytes(segment),
        }

    def _assign(self, meta: TableMeta, seg_name: str) -> List[str]:
        """Replica-group aware balanced placement: one server per replica
        group (replication R = R groups), least-loaded within the group."""
        with self._membership_lock:
            live = set(self.live)
            groups = dict(self.replica_group)
        if not live:
            raise RuntimeError("no live servers to assign to")
        loads = {s: 0 for s in live}
        for segs in meta.ideal.values():
            for s in segs:
                if s in loads:
                    loads[s] += 1
        out: List[str] = []
        for g in range(self.num_replica_groups):
            members = [s for s in live if groups.get(s) == g]
            if not members:
                continue
            out.append(min(members, key=lambda s: (loads[s], s)))
        # a replica group with zero live members can't host its copy — top up
        # replication from the remaining live servers (availability over
        # strict group placement, like the reference's non-strict fallback)
        want = min(self.replication, len(live))
        remaining = [s for s in live if s not in out]
        while len(out) < want and remaining:
            pick = min(remaining, key=lambda s: (loads[s], s))
            remaining.remove(pick)
            out.append(pick)
        return out

    # -- views -----------------------------------------------------------
    def external_view(self, table: str) -> Dict[str, Set[str]]:
        """Ideal state filtered to LIVE servers — what the broker routes on
        (ExternalView analog)."""
        meta = self.tables[table]
        with self._membership_lock:
            live = set(self.live)
        return {seg: {s for s in servers if s in live} for seg, servers in meta.ideal.items()}

    # -- rebalance --------------------------------------------------------
    def rebalance(self, table: str, min_available_replicas: int = 1) -> Dict[str, int]:
        """Repair/redistribute assignment over the CURRENT live set.

        Contract (TableRebalancer.java:122-134): a segment never has fewer
        than min_available_replicas live copies during the move — new
        replicas are added (server.add_segment) BEFORE old ones drop."""
        meta = self.tables[table]
        moved = added = dropped = 0
        with self._membership_lock:
            live = set(self.live)
            servers = dict(self.servers)
        for seg_name in list(meta.ideal):
            current = meta.ideal[seg_name]
            desired = set(self._assign_for_rebalance(meta, seg_name))
            if desired == current:
                continue
            segment = self._find_segment_object(table, seg_name, current | live)
            if segment is None:
                continue  # no live copy to replicate from
            # add new replicas first (keeps availability)
            for s in sorted(desired - current):
                servers[s].add_segment(table, segment)
                added += 1
            survivors = {s for s in desired if s in live}
            for s in sorted(current - desired):
                if len(survivors) >= min_available_replicas and s in servers:
                    servers[s].drop_segment(table, seg_name)
                    dropped += 1
                else:
                    desired.add(s)  # keep the old copy: availability floor
            meta.ideal[seg_name] = desired
            moved += 1
        return {"segmentsMoved": moved, "replicasAdded": added, "replicasDropped": dropped}

    def _assign_for_rebalance(self, meta: TableMeta, seg_name: str) -> List[str]:
        return self._assign(meta, seg_name)

    def _find_segment_object(self, table: str, seg_name: str, candidates) -> Optional[ImmutableSegment]:
        with self._membership_lock:
            live = set(self.live)
            servers = dict(self.servers)
        for s in candidates:
            if s in live and s in servers:
                seg = servers[s].get_segment(table, seg_name)
                if seg is not None:
                    return seg
        return None

    # -- periodic tasks ---------------------------------------------------
    def run_retention(self, now_ms: Optional[int] = None) -> List[str]:
        """RetentionManager: drop segments whose time range fell out of the
        retention window."""
        now_ms = now_ms or int(time.time() * 1000)
        with self._membership_lock:
            servers = dict(self.servers)
        purged: List[str] = []
        unit_ms = {"DAYS": 86_400_000, "HOURS": 3_600_000, "MINUTES": 60_000}
        for table, meta in self.tables.items():
            sc = meta.config.segments
            if sc.retention_time_value is None:
                continue
            horizon = now_ms - sc.retention_time_value * unit_ms.get(sc.retention_time_unit, 86_400_000)
            for seg_name in list(meta.ideal):
                tr = meta.segment_meta.get(seg_name, {}).get("timeRange")
                if tr is not None and tr[1] is not None and tr[1] < horizon:
                    for s in meta.ideal.pop(seg_name):
                        if s in servers:
                            servers[s].drop_segment(table, seg_name)
                    meta.segment_meta.pop(seg_name, None)
                    purged.append(f"{table}/{seg_name}")
        return purged

    # -- liveness (Helix session-expiry analog) ---------------------------
    def heartbeat(self, server_name: str) -> None:
        """Servers call this periodically; check_liveness marks stale ones
        down (the failure-DETECTION half of SURVEY §5.3 — rebalance is the
        recovery half).  Staleness is measured on the monotonic clock: an
        NTP step on the wall clock must never mass-expire the fleet."""
        if not hasattr(self, "_heartbeats"):
            self._heartbeats: Dict[str, float] = {}
        self._heartbeats[server_name] = time.monotonic()
        # a recovered server resumes serving (Helix session re-establishment)
        with self._membership_lock:
            recovered = server_name in self.servers and server_name not in self.live
        if recovered:
            self.mark_up(server_name)

    def check_liveness(self, timeout_s: float = 30.0) -> List[str]:
        """Mark servers with stale heartbeats down; returns who was dropped."""
        now = time.monotonic()
        dropped = []
        with self._membership_lock:
            live = list(self.live)
        for name in live:
            hb = getattr(self, "_heartbeats", {}).get(name)
            if hb is not None and now - hb > timeout_s:
                self.mark_down(name)
                dropped.append(name)
        return dropped

    def run_periodic_tasks(self, heartbeat_timeout_s: float = 30.0) -> Dict[str, Any]:
        """One tick of the controller periodic-task set
        (ControllerPeriodicTask analog): liveness check, retention purge,
        realtime consumption step, auto-rebalance of tables with
        under-replicated segments, status report."""
        dropped = self.check_liveness(heartbeat_timeout_s)
        purged = self.run_retention()
        consumed = self.run_realtime_consumption(max_batches=4)
        status = self.status_report()
        rebalanced = []
        with self._membership_lock:
            any_live = bool(self.live)
        for table, st in status.items():
            if st["underReplicated"] and any_live:
                self.rebalance(table)
                rebalanced.append(table)
        return {
            "serversDropped": dropped,
            "segmentsPurged": purged,
            "rowsConsumed": consumed,
            "tablesRebalanced": rebalanced,
        }

    def start_periodic_tasks(self, interval_s: float = 5.0, stop_event=None) -> "threading.Thread":
        """Background periodic-task thread (daemonized)."""
        import threading

        import logging

        from pinot_tpu.utils.metrics import METRICS

        log = logging.getLogger("pinot_tpu.cluster")

        def loop():
            while stop_event is None or not stop_event.is_set():
                try:
                    self.run_periodic_tasks()
                except Exception:  # noqa: BLE001 — periodic tasks must not die
                    METRICS.counter("periodicTaskExceptions").inc()
                    log.exception("periodic task tick failed")
                time.sleep(interval_s)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t

    def status_report(self) -> Dict[str, Dict]:
        """SegmentStatusChecker: per-table replica health."""
        with self._membership_lock:
            live = set(self.live)
            servers = dict(self.servers)
        # per-server HBM reservation occupancy (admission ledger view)
        reserved = {}
        for name, srv in servers.items():
            budget = getattr(srv, "budget", None)
            if budget is not None:
                reserved[name] = budget.snapshot()
        out: Dict[str, Dict] = {}
        for table, meta in self.tables.items():
            under = []
            for seg, seg_servers in meta.ideal.items():
                n_live = sum(1 for s in seg_servers if s in live)
                if n_live < min(self.replication, len(seg_servers)) or n_live == 0:
                    under.append(seg)
            out[table] = {
                "segments": len(meta.ideal),
                "underReplicated": under,
                "liveServers": sorted(live),
                "reservedBytes": reserved,
            }
        return out
