"""Segment deep store: durable segment home behind the PinotFS SPI.

Reference parity: Pinot's segment deep store (controller data dir / S3) and
the segment-completion protocol — a sealed or uploaded segment is copied to
the deep store BEFORE its metadata commits, so any server holding it in HBM
can be killed and re-materialized from durable storage (the Taurus
separation of durable storage from serving compute, PAPERS.md).  Layout:

  {root}/{table}/{segment_name}/columns.bin + metadata.json

Upload commits by directory rename: the segment is staged under
`.staging-{name}`, then moved into place — readers either see a complete
segment directory or none (kill-point `deepstore.upload.before_commit`
between the copy and the move proves it).  Downloads verify size + CRC32
against the committed metadata before the local copy is trusted; a corrupt
local segment is quarantined and re-downloaded.
"""
from __future__ import annotations

import logging
import os
import shutil
from typing import List, Optional

from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.segment.store import SegmentCorruptError, verify_segment
from pinot_tpu.spi.filesystem import PinotFS, fs_for_uri, fsync_dir, strip_scheme
from pinot_tpu.utils.crashpoints import crash_point
from pinot_tpu.utils.metrics import METRICS

log = logging.getLogger("pinot_tpu.cluster")


class SegmentDeepStore:
    """Durable table/segment tree over a PinotFS (local first-party; cloud
    schemes via spi.filesystem.register_fs)."""

    def __init__(self, root_uri: str, fs: Optional[PinotFS] = None):
        self.root = strip_scheme(root_uri)
        self.fs = fs if fs is not None else fs_for_uri(root_uri)
        self.fs.mkdir(self.root)

    # -- paths -----------------------------------------------------------
    def segment_uri(self, table: str, name: str) -> str:
        return os.path.join(self.root, table, name)

    def _staging_uri(self, table: str, name: str) -> str:
        return os.path.join(self.root, table, f".staging-{name}")

    # -- queries ---------------------------------------------------------
    def has_segment(self, table: str, name: str) -> bool:
        return self.fs.exists(os.path.join(self.segment_uri(table, name), "metadata.json"))

    def list_segments(self, table: str) -> List[str]:
        tdir = os.path.join(self.root, table)
        if not self.fs.exists(tdir):
            return []
        out = []
        for p in self.fs.list_files(tdir):
            base = os.path.basename(p.rstrip("/"))
            if not base.startswith(".staging-") and self.fs.exists(os.path.join(p, "metadata.json")):
                out.append(base)
        return sorted(out)

    # -- upload (segment completion: copy -> verify -> commit-by-rename) --
    def upload(self, table: str, local_dir: str, name: Optional[str] = None) -> str:
        """Copy a sealed local segment directory into the deep store.
        Idempotent: re-uploading an already-committed segment is a no-op
        (the first committed copy wins — segment content is immutable)."""
        name = name or os.path.basename(os.path.normpath(local_dir))
        if self.has_segment(table, name):
            return self.segment_uri(table, name)
        verify_segment(local_dir)  # never upload a torn local build
        staging = self._staging_uri(table, name)
        if self.fs.exists(staging):
            self.fs.delete(staging, force=True)  # stale crash leftover
        self.fs.copy_from_local(local_dir, staging)
        crash_point("deepstore.upload.before_commit")
        final = self.segment_uri(table, name)
        if self.fs.exists(final):  # lost a concurrent-upload race: fine
            self.fs.delete(staging, force=True)
        else:
            self.fs.move(staging, final)
        fsync_dir(os.path.dirname(final))
        crash_point("deepstore.upload.after_commit")
        METRICS.counter("deepstore.uploads").inc()
        return final

    def put_segment(self, table: str, segment: ImmutableSegment) -> Optional[str]:
        """Upload a segment object, serializing it first if it was built
        in-memory (no durable source_dir yet).  Returns the deep-store URI,
        or None for consuming-segment snapshots (not yet durable by
        design — uncommitted rows replay from the stream)."""
        if getattr(segment, "in_memory", False):
            return None
        if self.has_segment(table, segment.name):
            return self.segment_uri(table, segment.name)
        src = segment.source_dir
        if src is None or not os.path.isdir(src):
            staging = self._staging_uri(table, f"build-{segment.name}")
            if os.path.isdir(staging):
                shutil.rmtree(staging)
            os.makedirs(os.path.dirname(staging), exist_ok=True)
            segment.save(staging)
            try:
                return self.upload(table, staging, name=segment.name)
            finally:
                shutil.rmtree(staging, ignore_errors=True)
        return self.upload(table, src, name=segment.name)

    # -- download (restart recovery: fetch -> verify -> commit-by-rename) --
    def download(self, table: str, name: str, local_dir: str) -> str:
        """Materialize a deep-store segment at {local_dir}/{name}, verified.
        An existing VALID local copy is reused; a corrupt one is quarantined
        aside and re-fetched."""
        dst = os.path.join(local_dir, name)
        if os.path.isdir(dst):
            try:
                verify_segment(dst)
                return dst
            except SegmentCorruptError as e:
                METRICS.counter("deepstore.corruptLocalCopies").inc()
                aside = dst + ".corrupt"
                shutil.rmtree(aside, ignore_errors=True)
                os.replace(dst, aside)
                log.warning("quarantined corrupt local segment %s (%s)", dst, e)
        src = self.segment_uri(table, name)
        if not self.has_segment(table, name):
            raise FileNotFoundError(f"deep store has no segment {table}/{name}")
        tmp = dst + ".download"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(local_dir, exist_ok=True)
        self.fs.copy_to_local(src, tmp)
        verify_segment(tmp)  # reject a torn/corrupt transfer before commit
        crash_point("deepstore.download.before_commit")
        os.replace(tmp, dst)
        fsync_dir(local_dir)
        METRICS.counter("deepstore.downloads").inc()
        return dst

    def fetch_segment(self, table: str, name: str, local_dir: str) -> ImmutableSegment:
        """Download (or reuse a verified local copy) and load, CRC-checked."""
        return ImmutableSegment.load(self.download(table, name, local_dir), verify=True)
