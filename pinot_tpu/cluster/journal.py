"""Coordinator metadata journal: fsync'd JSONL log + compacted snapshots.

Reference parity: the ZK property store under Helix — every ideal-state /
segment-metadata mutation the reference persists to ZooKeeper (and recovers
by reading back on controller restart) appends here instead.  The layout is
the classic WAL-plus-snapshot pair:

  {meta_dir}/journal.jsonl   one JSON object per line: {"seq": N, "op": ...}
  {meta_dir}/snapshot.json   {"seq": N, "state": {...}} — state after entry N

Append discipline: write line -> flush -> fsync (kill-point
`journal.append.after_write` sits between write and fsync, proving a torn
tail is recovered, not fatal).  Compaction writes the snapshot via
tmp-fsync-replace, then truncates the journal the same way — a crash
between the two replays already-snapshotted entries, which every `op`
handler tolerates by being idempotent (set-valued ideal state, last-writer
checkpoint pointers).

Recovery tolerates exactly the artifacts crashes produce: a truncated final
journal line is dropped AND truncated off the file (it never committed — its
fsync didn't return; cutting the partial bytes means a later append can
never concatenate onto them into one garbled line); a corrupt snapshot is
quarantined aside (`.corrupt-N`) and the previous snapshot
(`snapshot.json.bak`) or empty state is used; stale `*.tmp` files are swept.

Round 18 adds the EPOCH FENCE (cluster/election.py): when a LeaseManager is
attached as `self.fence`, every append re-validates the durable lease under
the journal lock and stamps the entry with the writer's epoch; an append
from a deposed epoch raises FencedEpochError before any byte reaches the
log (counter `coordinator.fencedAppends`), and replay drops any
epoch-regressed interleaving a torn race still managed to leave behind.
"""
from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from pinot_tpu.cluster.election import NotLeaderError
from pinot_tpu.spi.filesystem import durable_write_json, fsync_dir, sweep_tmp
from pinot_tpu.utils.crashpoints import crash_point
from pinot_tpu.utils.metrics import METRICS

log = logging.getLogger("pinot_tpu.cluster")

JOURNAL_FILE = "journal.jsonl"
SNAPSHOT_FILE = "snapshot.json"


def _quarantine(path: str) -> Optional[str]:
    """Rename a corrupt file aside (never delete evidence); returns the new
    path or None if the rename itself failed."""
    for i in range(1000):
        aside = f"{path}.corrupt-{i}"
        if not os.path.exists(aside):
            try:
                os.replace(path, aside)
                return aside
            except OSError:
                log.exception("could not quarantine corrupt file %s", path)
                return None
    return None


class MetaJournal:
    """Append-ordered durable log of coordinator state mutations."""

    def __init__(self, meta_dir: str, compact_every: int = 256):
        self.meta_dir = meta_dir
        self.compact_every = max(1, int(compact_every))
        os.makedirs(meta_dir, exist_ok=True)
        sweep_tmp(meta_dir)
        self._lock = threading.Lock()
        self._fh = None  # lazily (re)opened append handle
        self.seq = 0  # last durably appended entry seq
        self.appended_since_snapshot = 0
        # LeaseManager epoch fence (cluster/election.py); None = unfenced
        # (a coordinator without an election, or legacy callers)
        self.fence = None
        # FaultPlan hook for the journal_append_latency rule
        self.fault_plan = None

    # -- paths -----------------------------------------------------------
    @property
    def journal_path(self) -> str:
        return os.path.join(self.meta_dir, JOURNAL_FILE)

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.meta_dir, SNAPSHOT_FILE)

    # -- append ----------------------------------------------------------
    def append(self, op: str, **data: Any) -> int:
        """Durably append one mutation; returns its seq.  The entry is
        committed once fsync returns — a crash before that point loses (at
        most) a torn final line, which load() drops."""
        with self._lock:
            plan = self.fault_plan
            if plan is not None:
                plan.on_journal_append(
                    self.fence.node_id if self.fence is not None else "journal"
                )
            epoch = 0
            if self.fence is not None:
                try:
                    epoch = self.fence.validate_writer()
                except NotLeaderError:
                    # a deposed writer: refuse BEFORE any byte hits the log
                    # (seq untouched — the entry never existed)
                    METRICS.counter("coordinator.fencedAppends").inc()
                    raise
            self.seq += 1
            # reserved keys win: an op payload must never clobber the
            # journal's own sequencing/fencing fields
            entry = dict(data)
            entry["seq"] = self.seq
            entry["op"] = op
            if self.fence is not None:
                entry["epoch"] = epoch
            line = json.dumps(entry, separators=(",", ":")) + "\n"
            if self._fh is None:
                self._fh = open(self.journal_path, "a", encoding="utf-8")
            self._fh.write(line)
            crash_point("journal.append.after_write")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.appended_since_snapshot += 1
            METRICS.counter("coordinator.journalAppends").inc()
            return self.seq

    def should_compact(self) -> bool:
        with self._lock:
            return self.appended_since_snapshot >= self.compact_every

    # -- snapshot / compaction -------------------------------------------
    def snapshot(self, state: Dict[str, Any]) -> None:
        """Write a compacted snapshot of `state` (which must reflect every
        entry up to self.seq), then truncate the journal.  Crash-ordering:
        snapshot commits BEFORE the journal truncates, so a crash between
        the two only re-applies idempotent entries on the next load."""
        with self._lock:
            seq = self.seq
            # keep the previous snapshot as the corruption fallback
            if os.path.exists(self.snapshot_path):
                os.replace(self.snapshot_path, self.snapshot_path + ".bak")
            crash_point("journal.snapshot.after_bak")
            durable_write_json(
                self.snapshot_path,
                {"seq": seq, "state": state},
                crash_prefix="journal.snapshot",
            )
            crash_point("journal.snapshot.before_truncate")
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            tmp = self.journal_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.journal_path)
            fsync_dir(self.meta_dir)
            self.appended_since_snapshot = 0
            METRICS.counter("coordinator.journalCompactions").inc()

    # -- load ------------------------------------------------------------
    def load(self) -> Tuple[Optional[Dict[str, Any]], List[Dict[str, Any]]]:
        """Read (snapshot_state, entries-after-snapshot) from disk,
        recovering from every crash artifact the commit paths can produce.
        Also positions self.seq after the last committed entry so appends
        continue the sequence."""
        with self._lock:
            sweep_tmp(self.meta_dir)
            state, snap_seq = self._load_snapshot_locked()
            entries = self._load_journal_locked(after_seq=snap_seq)
            self.seq = max(snap_seq, entries[-1]["seq"] if entries else 0)
            self.appended_since_snapshot = len(entries)
            return state, entries

    def _load_snapshot_locked(self) -> Tuple[Optional[Dict[str, Any]], int]:
        for path in (self.snapshot_path, self.snapshot_path + ".bak"):
            if not os.path.exists(path):
                continue
            try:
                with open(path, "r", encoding="utf-8") as f:
                    doc = json.load(f)
                return doc.get("state") or {}, int(doc.get("seq", 0))
            except (json.JSONDecodeError, OSError, ValueError, TypeError) as e:
                METRICS.counter("coordinator.snapshotCorrupt").inc()
                aside = _quarantine(path)
                log.warning(
                    "corrupt coordinator snapshot %s (%s) quarantined to %s", path, e, aside
                )
        return None, 0

    def _load_journal_locked(self, after_seq: int) -> List[Dict[str, Any]]:
        path = self.journal_path
        if not os.path.exists(path):
            return []
        entries: List[Dict[str, Any]] = []
        raw_lines: List[Tuple[int, bytes]] = []  # (byte offset, raw line)
        with open(path, "rb") as f:
            off = 0
            for raw in iter(f.readline, b""):
                raw_lines.append((off, raw))
                off += len(raw)
        last_seq = after_seq
        max_epoch = 0
        for i, (off, raw) in enumerate(raw_lines):
            line = raw.decode("utf-8", errors="replace").strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
                seq = int(entry["seq"])
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                if i == len(raw_lines) - 1 or not raw.endswith(b"\n"):
                    # torn final line: the append died before fsync — that
                    # entry never committed.  Drop it AND cut the partial
                    # bytes off the file, so the next append starts a fresh
                    # line instead of concatenating into garbage
                    METRICS.counter("coordinator.journalTornTail").inc()
                    log.warning("truncating torn journal tail line in %s", path)
                    self._truncate_at_locked(off)
                    break
                # mid-file corruption: quarantine the whole log; committed
                # state up to the snapshot survives
                METRICS.counter("coordinator.journalCorrupt").inc()
                aside = _quarantine(path)
                log.error("corrupt journal %s quarantined to %s", path, aside)
                if self._fh is not None:
                    self._fh.close()
                    self._fh = None
                return entries
            if seq <= last_seq:
                continue  # replay overlap after a crash mid-compaction
            epoch = int(entry.get("epoch", 0) or 0)
            if epoch < max_epoch:
                # interleaving from a deposed epoch (belt to the append
                # fence's suspenders): replay ignores it
                METRICS.counter("coordinator.fencedReplayDropped").inc()
                continue
            if epoch > max_epoch:
                max_epoch = epoch
            last_seq = seq
            entries.append(entry)
        return entries

    def _truncate_at_locked(self, offset: int) -> None:
        """Cut the journal back to `offset` (torn-tail recovery).  Best
        effort: a failure here just leaves the pre-r18 behavior (the torn
        line stays on disk and keeps being dropped at every load)."""
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        try:
            with open(self.journal_path, "r+b") as f:
                f.truncate(offset)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            log.exception("could not truncate torn journal tail in %s", self.journal_path)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
