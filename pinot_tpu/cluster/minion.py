"""Minion tasks: merge/rollup, purge, realtime-to-offline.

Reference parity: the minion framework (PinotTaskManager + TaskGenerator
planning Helix tasks, PinotTaskExecutor running them —
pinot-controller/.../helix/core/minion/PinotTaskManager.java,
pinot-minion/.../minion/executor/PinotTaskExecutor.java) and the built-in
tasks (pinot-plugins/pinot-minion-builtin-tasks/.../tasks/{mergerollup,
purge,realtimetoofflinesegments}).

Re-design: no Helix task queues — a task run is generate() (inspect
coordinator metadata, emit work items) followed by execute() (segment
rebuilds through the ordinary builder), with the same atomic
add-new-then-drop-old segment swaps the reference drives through the
controller.  Rollup/merge inherit the vectorized build path, so a "merge"
is one columnar concat + rebuild, not a row-by-row copy.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from pinot_tpu.cluster.coordinator import Coordinator
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.segment import ImmutableSegment


def _concat_columns(schema, segments: List[ImmutableSegment]) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    for f in schema.fields:
        parts = [seg.column(f.name).decoded() for seg in segments]
        nulls = [seg.column(f.name).nulls for seg in segments]
        arrs = []
        for vals, nm in zip(parts, nulls):
            vals = np.asarray(vals)
            if nm is not None and nm.any():
                vals = np.asarray(vals, dtype=object)
                vals[nm] = None
            arrs.append(vals)
        if any(a.dtype == object for a in arrs):
            arrs = [np.asarray(a, dtype=object) for a in arrs]
        out[f.name] = np.concatenate(arrs)
    return out


class MinionTaskManager:
    """Task registry + runner (PinotTaskManager analog)."""

    def __init__(self, coordinator: Coordinator):
        self.coordinator = coordinator
        self.tasks: Dict[str, Callable[..., Dict[str, Any]]] = {
            "MergeRollupTask": self.merge_rollup,
            "PurgeTask": self.purge,
            "RealtimeToOfflineSegmentsTask": self.realtime_to_offline,
            "UpsertCompactionTask": self.upsert_compact,
            "RefreshSegmentTask": self.refresh,
        }

    def run(self, task_type: str, table: str, **kw) -> Dict[str, Any]:
        fn = self.tasks.get(task_type)
        if fn is None:
            raise ValueError(f"unknown minion task {task_type!r} (have {sorted(self.tasks)})")
        return fn(table, **kw)

    # ------------------------------------------------------------------
    def _segment_objects(self, table: str, names: List[str]) -> List[ImmutableSegment]:
        segs = []
        for n in names:
            obj = self.coordinator._find_segment_object(table, n, self.coordinator.live)
            if obj is not None:
                segs.append(obj)
        return segs

    def _swap(self, table: str, new_segments: List[ImmutableSegment], old_names: List[str]) -> None:
        """Atomic-enough replacement: add merged segments, then drop inputs
        (the reference's segment-replacement protocol ordering)."""
        meta = self.coordinator.tables[table]
        for seg in new_segments:
            self.coordinator.add_segment(table, seg)
        for name in old_names:
            for s in meta.ideal.pop(name, set()):
                if s in self.coordinator.servers:
                    self.coordinator.servers[s].drop_segment(table, name)
            meta.segment_meta.pop(name, None)

    # -- MergeRollupTask -------------------------------------------------
    def merge_rollup(
        self,
        table: str,
        max_rows_per_segment: int = 1 << 20,
        min_input_segments: int = 2,
        rollup: bool = False,
    ) -> Dict[str, Any]:
        """Merge small segments into bigger ones; optional rollup collapses
        duplicate dimension combos by re-aggregating metrics (SUM)."""
        coord = self.coordinator
        meta = coord.tables[table]
        small = [
            n
            for n in meta.ideal
            if meta.segment_meta.get(n, {}).get("numDocs", 0) < max_rows_per_segment
        ]
        if len(small) < min_input_segments:
            return {"merged": 0, "inputs": []}
        segments = self._segment_objects(table, small)
        if len(segments) < min_input_segments:
            return {"merged": 0, "inputs": []}
        schema = meta.schema
        data = _concat_columns(schema, segments)
        if rollup:
            data = self._rollup(schema, data)
        name = f"{table}_merged_{int(time.time() * 1000) % 10_000_000}"
        out_rows = len(next(iter(data.values()))) if data else 0
        merged = build_segment(schema, data, name, table_config=meta.config)
        self._swap(table, [merged], small)
        return {"merged": 1, "inputs": small, "outputSegment": name, "outputRows": out_rows}

    @staticmethod
    def _rollup(schema, data: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        from pinot_tpu.spi.schema import FieldRole

        dims = [f.name for f in schema.fields if f.role is not FieldRole.METRIC]
        metrics = [f.name for f in schema.fields if f.role is FieldRole.METRIC]
        if not dims or not metrics:
            return data
        n = len(data[dims[0]])
        seen: Dict[tuple, int] = {}
        inverse = np.empty(n, dtype=np.int64)
        reps: List[int] = []
        for i in range(n):
            key = tuple(data[d][i] for d in dims)
            j = seen.get(key)
            if j is None:
                j = seen[key] = len(reps)
                reps.append(i)
            inverse[i] = j
        sel = np.asarray(reps, dtype=np.int64)
        out: Dict[str, np.ndarray] = {}
        for d in dims:
            out[d] = np.asarray(data[d])[sel]
        for m in metrics:
            raw = data[m]
            # nullable metrics: None/NaN contribute 0, matching SUM's
            # ignore-nulls semantics (NaN would poison the whole group)
            vals = np.array(
                [0.0 if v is None or (isinstance(v, float) and v != v) else float(v) for v in raw],
                dtype=np.float64,
            )
            out[m] = np.bincount(inverse, weights=vals, minlength=len(sel))
        return out

    # -- PurgeTask -------------------------------------------------------
    def purge(self, table: str, purge_fn: Optional[Callable[[Dict[str, Any]], bool]] = None) -> Dict[str, Any]:
        """Rebuild segments dropping rows purge_fn marks (RecordPurger
        analog — the GDPR-delete path)."""
        if purge_fn is None:
            raise ValueError("PurgeTask needs purge_fn(row_dict) -> bool (True = drop)")
        coord = self.coordinator
        meta = coord.tables[table]
        purged_rows = 0
        rebuilt = []
        for name in list(meta.ideal):
            seg = coord._find_segment_object(table, name, coord.live)
            if seg is None:
                continue
            cols = {f.name: seg.column(f.name).decoded() for f in meta.schema.fields}
            n = seg.num_docs
            drop = np.array(
                [purge_fn({k: cols[k][i] for k in cols}) for i in range(n)], dtype=bool
            )
            if not drop.any():
                continue
            keep = ~drop
            purged_rows += int(drop.sum())
            data = {k: np.asarray(v, dtype=object)[keep] if np.asarray(v).dtype == object else np.asarray(v)[keep] for k, v in cols.items()}
            new_name = f"{name}_purged"
            new_seg = build_segment(meta.schema, data, new_name, table_config=meta.config)
            self._swap(table, [new_seg], [name])
            rebuilt.append(new_name)
        return {"purgedRows": purged_rows, "rebuiltSegments": rebuilt}

    # -- RealtimeToOfflineSegmentsTask ----------------------------------
    def realtime_to_offline(
        self,
        table: str,
        realtime_manager=None,
        offline_table: Optional[str] = None,
        window_end_ms: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Move sealed realtime segments whose time range closed before the
        window end into the offline table, advancing a watermark kept in the
        coordinator metadata (RealtimeToOfflineSegmentsTaskGenerator's
        watermark semantics)."""
        if realtime_manager is None:
            raise ValueError("RealtimeToOfflineSegmentsTask needs the RealtimeTableDataManager")
        offline_table = offline_table or f"{table}_OFFLINE"
        coord = self.coordinator
        if offline_table not in coord.tables:
            coord.add_table(realtime_manager.schema, _offline_config(realtime_manager.config, offline_table))
        meta = coord.tables[offline_table]
        watermark = meta.segment_meta.get("__rto_watermark__", {}).get("value", 0)
        window_end_ms = window_end_ms or int(time.time() * 1000)
        moved = []
        for p, sealed_list in realtime_manager.sealed.items():
            remaining = []
            for seg in sealed_list:
                tr = seg.time_range
                if tr is not None and tr[1] is not None and watermark <= tr[1] < window_end_ms:
                    data = {f.name: seg.column(f.name).decoded() for f in realtime_manager.schema.fields}
                    off = build_segment(
                        realtime_manager.schema,
                        data,
                        f"{offline_table}__{seg.name}",
                        table_config=meta.config,
                    )
                    coord.add_segment(offline_table, off)
                    moved.append(seg.name)
                else:
                    remaining.append(seg)
            realtime_manager.sealed[p] = remaining
        meta.segment_meta["__rto_watermark__"] = {"value": window_end_ms}
        return {"moved": moved, "watermarkMs": window_end_ms, "offlineTable": offline_table}

    # -- UpsertCompactionTask --------------------------------------------
    def upsert_compact(
        self,
        table: str,
        realtime_manager=None,
        invalid_threshold: float = 0.1,
    ) -> Dict[str, Any]:
        """Rewrite sealed realtime segments whose upsert validDocIds mask
        carries >= invalid_threshold masked-out rows, dropping them
        physically (UpsertCompactionTaskExecutor analog — the reference
        reads the server's validDocIds snapshot the same way).

        Compaction preserves surviving-row order (no re-sort), so the
        partition upsert manager's pk_map locations remap through the
        kept-row prefix; the fresh mask is all-true.  The swap is
        in-memory — on restart the manager replays raw rows and rebuilds
        equivalent masks (bootstrap path), so durability is unaffected."""
        import dataclasses

        rt = realtime_manager or self.coordinator.realtime.get(table)
        if rt is None or getattr(rt, "upsert", None) is None:
            raise ValueError(f"UpsertCompactionTask needs an upsert-enabled realtime table ({table!r})")
        um = rt.upsert
        cfg = dataclasses.replace(
            rt.config, indexing=dataclasses.replace(rt.config.indexing, sorted_column=None)
        )
        report = {"compacted": [], "rowsDropped": 0}
        remaps: Dict[str, Dict[int, int]] = {}  # segment -> old doc -> new doc
        for p, sealed_list in rt.sealed.items():
            out_list = []
            for seg in sealed_list:
                mask = seg.valid_docs
                inv = int((~np.asarray(mask, dtype=bool)).sum()) if mask is not None else 0
                if inv == 0 or inv / max(1, seg.num_docs) < invalid_threshold:
                    out_list.append(seg)
                    continue
                keep = np.nonzero(np.asarray(mask, dtype=bool))[0]
                data = _concat_columns(rt.schema, [seg])
                data = {k: v[keep] for k, v in data.items()}
                new_seg = build_segment(rt.schema, data, seg.name, cfg)
                remaps[seg.name] = {int(d): j for j, d in enumerate(keep)}
                fresh = np.ones(len(keep), dtype=bool)
                um.valid[seg.name] = fresh
                new_seg.valid_docs = fresh
                out_list.append(new_seg)
                report["compacted"].append(seg.name)
                report["rowsDropped"] += inv
            rt.sealed[p] = out_list
        if remaps:  # one pk_map pass for all compacted segments
            for loc in um.pk_map.values():
                m = remaps.get(loc.segment)
                if m is None:
                    continue
                # a tracked doc missing from the kept set was itself invalid
                # (a delete tombstone's own row): mark it compacted-away so
                # later invalidations/reads don't touch a reused index
                # (review-caught stale-location bug)
                loc.doc = m.get(loc.doc, -1)
        return report

    # -- RefreshSegmentTask ----------------------------------------------
    def refresh(self, table: str, segment_name: Optional[str] = None) -> Dict[str, Any]:
        """Rebuild offline segments with the table's CURRENT config/schema —
        picks up newly configured indexes, sort columns, dictionary changes
        (RefreshSegmentTaskExecutor analog)."""
        meta = self.coordinator.tables[table]
        names = [segment_name] if segment_name else list(meta.ideal)
        refreshed = []
        for name in names:
            segs = self._segment_objects(table, [name])
            if not segs:
                continue
            data = _concat_columns(meta.schema, segs)
            new_seg = build_segment(meta.schema, data, name, meta.config)
            # drop the old assignment, then re-add under the same name
            for s in meta.ideal.pop(name, set()):
                if s in self.coordinator.servers:
                    self.coordinator.servers[s].drop_segment(table, name)
            meta.segment_meta.pop(name, None)
            self.coordinator.add_segment(table, new_seg)
            refreshed.append(name)
        return {"refreshed": refreshed}


def _offline_config(cfg, name: str):
    import dataclasses

    from pinot_tpu.spi.config import TableType

    return dataclasses.replace(cfg, name=name, table_type=TableType.OFFLINE, stream=None)
