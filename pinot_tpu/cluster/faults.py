"""Deterministic fault injection for the cluster layer.

Reference parity: Pinot exercises its failover paths with integration tests
that kill servers mid-query (e.g. OfflineGRPCServerIntegrationTest /
ServerStarter restarts); here the same chaos is scripted as data.  A
FaultPlan is a seeded, reproducible schedule of faults keyed by (server,
call number): fail server S on its Nth scatter call, add fixed latency,
drop a segment from its local view, flap coordinator liveness, CRASH a
server (process death: its segment state is lost and recovery is a full
coordinator-driven restart + deep-store reconcile) or restart a crashed
one mid-workload.  Hooks live in ServerInstance.execute (on_execute /
segment_dropped) and the coordinator (mark_down / mark_up / crash_server /
restart_server), so every failover/quarantine/partial-result path in the
broker is driven by tier-1 tests instead of hoped-for.  Orthogonally,
kill_at() arms named kill-points (utils/crashpoints.py) sitting between
the write/rename/swap steps of every commit path — segment seal, journal
append, snapshot compaction, deep-store upload/download, rebalance move —
so crash-recovery tests can die at EXACTLY one protocol step and assert
the restart converges to committed state.

Gray failures get first-class rules too: jitter() draws seeded lognormal
per-call delays (keyed on (seed, server, call) so thread interleaving can't
change the sequence), slow_ramp() degrades latency linearly toward a cap,
gray_flap() alternates slow/fast phases, and partition(src, dst) drops
src->dst calls one-way while dst->src keeps working.  All delays go through
the injectable `plan.sleep`, so tier-1 tests swap in a fake clock and never
block.

Round 18 adds the CONTROL-PLANE fault family for coordinator HA
(cluster/election.py): pause_leader() freezes a coordinator (every
control-plane entry point refuses, lease renewals silently stop — the GC
pause that outlives lease expiry), resume_leader() thaws it into the epoch
fence, lease_clock_skew() offsets one node's view of cluster time, and
journal_append_latency() delays durable appends (fsync stall).  Hooks live
in LeaseManager.now/renew and MetaJournal.append via attach_coordinator().

Determinism contract: the same plan (same seed, same builder calls) applied
to an identically-built cluster produces the same fault sequence, hence the
same BrokerResponse — asserted by tests/test_fault_tolerance.py.
"""
from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple


class ServerFaultError(RuntimeError):
    """Injected server-side failure — the harness' stand-in for a crashed or
    unreachable server (the broker must treat it like any transport error)."""


@dataclass
class _Rule:
    kind: str  # "fail" | "latency" | "jitter" | "slow_ramp" | "gray_flap" | "partition" | "flap_down" | "flap_up" | "crash" | "restart"
    trigger: str  # server whose call counter drives the rule
    target: str  # server the effect applies to (== trigger for fail/latency)
    calls: Optional[Set[int]] = None  # 1-based call numbers; None = every call
    ms: float = 0.0
    message: str = ""
    sigma: float = 0.0  # lognormal shape for "jitter"
    cap_ms: float = 0.0  # latency ceiling for "jitter"/"slow_ramp" (0 = none)
    period: int = 0  # phase length in calls for "gray_flap"
    source: Optional[str] = None  # caller that the "partition" rule drops
    start_call: int = 1  # first call a "slow_ramp" counts from


# fail/crash raise (crash of the trigger itself), so side-effecting rules on
# the same call apply first; restarts precede crashes so a restart+crash pair
# scheduled on one call nets out to "bounced then died" deterministically
_APPLY_ORDER = {
    "latency": 0,
    "jitter": 0,
    "slow_ramp": 0,
    "gray_flap": 0,
    "restart": 1,
    "flap_down": 2,
    "flap_up": 2,
    "crash": 3,
    "partition": 4,
    "fail": 4,
}


class FaultPlan:
    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.sleep = time.sleep  # injectable for clock-free tests
        self.log: List[Tuple] = []  # (server, call_n, kind, detail) as applied
        self._rules: List[_Rule] = []
        self._dropped: Set[Tuple[str, str, str]] = set()  # (server, table, segment)
        self._calls: Dict[str, int] = {}
        self._coordinator = None
        self._lock = threading.Lock()
        self._kill_points: List[str] = []  # armed via kill_at, for reset
        # control-plane fault state (coordinator HA): paused leader node
        # ids, per-node lease clock skew, per-node journal append latency,
        # and the coordinators wired via attach_coordinator (keyed by
        # node_id — one entry per cluster coordinator)
        self._paused_leaders: Set[str] = set()
        self._lease_skew_ms: Dict[str, float] = {}
        self._journal_latency_ms: Dict[str, float] = {}
        self._journal_appends: Dict[str, int] = {}
        self._coordinators: Dict[str, object] = {}

    # -- wiring ----------------------------------------------------------
    def attach(self, coordinator) -> "FaultPlan":
        """Install the plan into every registered server + the coordinator
        (servers registered later can be given `server.fault_plan = plan`)."""
        self._coordinator = coordinator
        for s in coordinator.servers.values():
            s.fault_plan = self
        self.attach_coordinator(coordinator)
        return self

    def attach_coordinator(self, coordinator) -> "FaultPlan":
        """Wire the control-plane fault hooks (lease skew, renew
        suppression, journal append latency) into one coordinator — call it
        for the leader AND each standby; attach() covers the leader."""
        self._coordinators[getattr(coordinator, "node_id", "coordinator")] = coordinator
        coordinator.fault_plan = self
        election = getattr(coordinator, "election", None)
        if election is not None:
            election.fault_plan = self
        journal = getattr(coordinator, "journal", None)
        if journal is not None:
            journal.fault_plan = self
        return self

    # -- plan builders (chainable) ----------------------------------------
    def fail_server(self, server: str, on_call: int = 1, times: int = 1, message: str = "") -> "FaultPlan":
        """Raise ServerFaultError on the server's Nth..N+times-1th execute."""
        # test-harness plan builder, not a serving path: rules are bounded by
        # the test script that authors them
        self._rules.append(  # pinot-lint: disable=W015
            _Rule("fail", server, server, calls=set(range(on_call, on_call + times)), message=message)
        )
        return self

    def always_fail(self, server: str, message: str = "") -> "FaultPlan":
        self._rules.append(_Rule("fail", server, server, calls=None, message=message))
        return self

    def add_latency(self, server: str, ms: float, on_call: Optional[int] = None) -> "FaultPlan":
        """Sleep `ms` at the top of the server's execute (every call when
        on_call is None) — the slow-replica / network-delay fault."""
        calls = None if on_call is None else {on_call}
        self._rules.append(_Rule("latency", server, server, calls=calls, ms=ms))
        return self

    def jitter(self, server: str, base_ms: float, sigma: float = 0.5, cap_ms: float = 0.0) -> "FaultPlan":
        """Seeded lognormal latency jitter on every call: the per-call delay is
        ``base_ms * lognormvariate(0, sigma)`` drawn from a generator keyed on
        (plan seed, server, call number), so the sequence is bit-identical
        across runs AND independent of thread interleaving — call N always
        draws the same delay no matter which worker reaches it first."""
        # plan builder (test-authored, bounded), not a serving path
        self._rules.append(  # pinot-lint: disable=W015
            _Rule("jitter", server, server, ms=base_ms, sigma=sigma, cap_ms=cap_ms)
        )
        return self

    def slow_ramp(self, server: str, ms_per_call: float, cap_ms: float, from_call: int = 1) -> "FaultPlan":
        """Gray degradation: latency grows linearly with each call —
        ``min(cap_ms, ms_per_call * calls_since_start)`` — modeling a server
        that is slowly dying (GC spiral, disk filling) without ever erroring."""
        # plan builder (test-authored, bounded), not a serving path
        self._rules.append(  # pinot-lint: disable=W015
            _Rule("slow_ramp", server, server, ms=ms_per_call, cap_ms=cap_ms, start_call=from_call)
        )
        return self

    def gray_flap(self, server: str, slow_ms: float, period: int = 4) -> "FaultPlan":
        """Gray flapping: the server alternates between a slow phase and a
        fast phase every `period` calls, starting slow — the hardest case for
        breakers (never errors) and for naive outlier detection (recovers
        just long enough to look healthy)."""
        # plan builder (test-authored, bounded), not a serving path
        self._rules.append(  # pinot-lint: disable=W015
            _Rule("gray_flap", server, server, ms=slow_ms, period=max(1, period))
        )
        return self

    def partition(self, src: str, dst: str, on_call: Optional[int] = None) -> "FaultPlan":
        """One-way network partition: calls FROM `src` TO `dst` drop with
        ServerFaultError while dst→src (and everyone else→dst) still works.
        The caller identity arrives via on_execute(..., source=...); the
        broker's scatter path identifies itself as source="broker"."""
        calls = None if on_call is None else {on_call}
        # plan builder (test-authored, bounded), not a serving path
        self._rules.append(  # pinot-lint: disable=W015
            _Rule("partition", dst, dst, calls=calls, source=src)
        )
        return self

    def drop_segment(self, server: str, table: str, segment: str) -> "FaultPlan":
        """The server behaves as if it never downloaded the segment (a lost
        local copy); routing there fails with KeyError and must fail over."""
        self._dropped.add((server, table, segment))
        return self

    def flap_down(self, server: str, on_call: int = 1, of: Optional[str] = None) -> "FaultPlan":
        """Mark `server` down in the coordinator when `of` (default: the
        server itself) receives its Nth call — mid-scatter liveness loss."""
        self._rules.append(_Rule("flap_down", of or server, server, calls={on_call}))
        return self

    def flap_up(self, server: str, on_call: int, of: Optional[str] = None) -> "FaultPlan":
        self._rules.append(_Rule("flap_up", of or server, server, calls={on_call}))
        return self

    def crash_server(self, server: str, on_call: int = 1, of: Optional[str] = None) -> "FaultPlan":
        """KILL `server` (process death: segment state lost, external view
        drops it) when `of` (default: the server itself) receives its Nth
        call.  Unlike fail_server, recovery requires restart_server — the
        coordinator reconciles the rebooted server from the deep store."""
        # plan builder (test-authored, bounded), not a serving path
        self._rules.append(_Rule("crash", of or server, server, calls={on_call}))  # pinot-lint: disable=W015
        return self

    def restart_server(self, server: str, on_call: int, of: Optional[str] = None) -> "FaultPlan":
        """Restart a crashed `server` when `of` receives its Nth call: the
        coordinator reboots it empty, reconciles from deep store / live
        peers, and mark_up heals broker breakers mid-workload."""
        # plan builder (test-authored, bounded), not a serving path
        self._rules.append(_Rule("restart", of or server, server, calls={on_call}))  # pinot-lint: disable=W015
        return self

    # -- control-plane rules (coordinator HA) ------------------------------
    def pause_leader(self, node_id: str) -> "FaultPlan":
        """Freeze a coordinator process (GC pause / VM stall): every
        control-plane entry point refuses with NotLeaderError and its lease
        renewals silently stop — hold it past lease expiry and a standby
        takes over.  resume_leader() thaws it STILL BELIEVING it leads;
        its next journal append is what the epoch fence exists to stop."""
        with self._lock:
            self._paused_leaders.add(node_id)
            self.log.append((node_id, 0, "pause_leader", node_id))  # pinot-lint: disable=W015
        coord = self._coordinators.get(node_id)
        if coord is not None:
            coord.pause()
        return self

    def resume_leader(self, node_id: str) -> "FaultPlan":
        with self._lock:
            self._paused_leaders.discard(node_id)
            self.log.append((node_id, 0, "resume_leader", node_id))  # pinot-lint: disable=W015
        coord = self._coordinators.get(node_id)
        if coord is not None:
            coord.resume()
        return self

    def lease_clock_skew(self, node_id: str, ms: float) -> "FaultPlan":
        """Skew one node's view of cluster time by `ms` (positive = its
        clock runs ahead): a skewed-ahead standby sees the lease expire
        early and races the takeover — the fence, not the clock, is what
        keeps the journal single-writer."""
        with self._lock:
            self._lease_skew_ms[node_id] = float(ms)
            self.log.append((node_id, 0, "lease_clock_skew", ms))  # pinot-lint: disable=W015
        return self

    def journal_append_latency(self, node_id: str, ms: float) -> "FaultPlan":
        """Stall every durable journal append on `node_id` by `ms` (a slow
        fsync / contended disk): widens the window between the fence check
        and the write, which the append-under-lock discipline must keep
        safe."""
        with self._lock:
            self._journal_latency_ms[node_id] = float(ms)
            self.log.append((node_id, 0, "journal_append_latency", ms))  # pinot-lint: disable=W015
        return self

    # control-plane hooks (called from LeaseManager / MetaJournal)
    def allow_lease_renew(self, node_id: str) -> bool:
        with self._lock:
            paused = node_id in self._paused_leaders
            if paused:
                self.log.append((node_id, 0, "renew_suppressed", node_id))  # pinot-lint: disable=W015
        return not paused

    def lease_skew_ms(self, node_id: str) -> float:
        with self._lock:
            return self._lease_skew_ms.get(node_id, 0.0)

    def on_journal_append(self, node_id: str) -> None:
        with self._lock:
            self._journal_appends[node_id] = self._journal_appends.get(node_id, 0) + 1
            n = self._journal_appends[node_id]
            ms = self._journal_latency_ms.get(node_id, 0.0)
            if ms > 0:
                self.log.append((node_id, n, "journal_append_latency", ms))  # pinot-lint: disable=W015
        if ms > 0:
            self.sleep(ms / 1000.0)

    def kill_at(self, point: str, hit: int = 1) -> "FaultPlan":
        """Arm a named kill-point (utils/crashpoints.py): the `hit`-th time
        execution reaches crash_point(point) — between two steps of a commit
        protocol — InjectedCrash raises, simulating death at that exact
        instant.  Disarms after firing so the post-restart retry commits."""
        from pinot_tpu.utils import crashpoints

        crashpoints.arm(point, hit=hit)
        self._kill_points.append(point)
        return self

    def reset_kill_points(self) -> "FaultPlan":
        """Disarm every kill-point this plan armed (test teardown)."""
        from pinot_tpu.utils import crashpoints

        for p in self._kill_points:
            crashpoints.disarm(p)
        self._kill_points.clear()
        return self

    def chaos(self, servers: List[str], p_fail: float, max_calls: int = 8) -> "FaultPlan":
        """Seeded random failures: each (server, call<=max_calls) fails with
        probability p_fail, drawn ONCE at plan-build time from the plan's
        rng — two plans with the same seed script identical chaos."""
        for s in servers:
            bad = {n for n in range(1, max_calls + 1) if self.rng.random() < p_fail}
            if bad:
                self._rules.append(_Rule("fail", s, s, calls=bad, message="chaos"))
        return self

    # -- deterministic draws ----------------------------------------------
    def _jitter_ms(self, rule: _Rule, server: str, n: int) -> float:
        """Lognormal delay for call `n`, keyed on (seed, server, n) through a
        throwaway generator (random.Random seeds strings via SHA-512, stable
        across processes) so concurrent servers can't perturb each other's
        draw order — the fault sequence stays bit-deterministic."""
        draw = random.Random(f"jitter:{self.seed}:{server}:{n}")
        ms = rule.ms * draw.lognormvariate(0.0, rule.sigma)
        if rule.cap_ms > 0:
            ms = min(ms, rule.cap_ms)
        return ms

    # -- runtime hooks (called from ServerInstance.execute) ----------------
    def on_execute(self, server_name: str, source: str = "broker") -> None:
        with self._lock:
            n = self._calls[server_name] = self._calls.get(server_name, 0) + 1
            due = [
                r
                for r in self._rules
                if r.trigger == server_name
                and (r.calls is None or n in r.calls)
                and (r.kind != "partition" or r.source == source)
            ]
        for r in sorted(due, key=lambda r: _APPLY_ORDER[r.kind]):
            detail = r.target
            if r.kind == "jitter":
                detail = round(self._jitter_ms(r, server_name, n), 6)
            elif r.kind == "slow_ramp":
                if n < r.start_call:
                    continue
                detail = min(r.cap_ms, r.ms * (n - r.start_call + 1))
            elif r.kind == "gray_flap":
                if ((n - 1) // r.period) % 2 != 0:
                    continue  # fast phase: no effect, no log entry
                detail = r.ms
            elif r.kind == "partition":
                detail = r.source
            # the fault ledger IS the harness product (tests slice it by
            # index); a deque can't slice, and plans live one test long
            with self._lock:
                self.log.append((server_name, n, r.kind, detail))  # pinot-lint: disable=W015
            if r.kind == "latency":
                self.sleep(r.ms / 1000.0)
            elif r.kind in ("jitter", "slow_ramp", "gray_flap"):
                self.sleep(detail / 1000.0)
            elif r.kind == "partition":
                raise ServerFaultError(
                    f"injected partition: {r.source}->{server_name} dropped (call {n})"
                )
            elif r.kind == "flap_down" and self._coordinator is not None:
                self._coordinator.mark_down(r.target)
            elif r.kind == "flap_up" and self._coordinator is not None:
                self._coordinator.mark_up(r.target)
            elif r.kind == "restart" and self._coordinator is not None:
                self._coordinator.restart_server(r.target)
            elif r.kind == "crash":
                if self._coordinator is not None:
                    self._coordinator.crash_server(r.target)
                if r.target == server_name:
                    # the in-flight call on the crashing server dies with it
                    raise ServerFaultError(
                        f"injected crash: server {server_name} died (call {n})"
                    )
            elif r.kind == "fail":
                raise ServerFaultError(
                    r.message or f"injected fault: server {server_name} died (call {n})"
                )

    def segment_dropped(self, server: str, table: str, segment: str) -> bool:
        if (server, table, segment) in self._dropped:
            with self._lock:
                n = self._calls.get(server, 0)
                self.log.append((server, n, "drop_segment", segment))
            return True
        return False

    def calls(self, server: str) -> int:
        """How many execute calls the server has received under this plan."""
        with self._lock:
            return self._calls.get(server, 0)
