"""Realtime ingestion: stream SPI, mutable segments, consume/seal/swap.

Reference parity map (SURVEY.md §3.3):
  stream.py   - pinot-spi/.../spi/stream/ (StreamConsumerFactory,
                PartitionGroupConsumer, MessageBatch, StreamPartitionMsgOffset)
  mutable.py  - pinot-segment-local/.../indexsegment/mutable/MutableSegmentImpl.java
  manager.py  - pinot-core/.../data/manager/realtime/RealtimeSegmentDataManager.java
                (consumeLoop :470, processStreamEvents :591, commitSegment :971)
                + RealtimeTableDataManager.java:97
"""
from pinot_tpu.realtime.stream import (
    FileStream,
    InMemoryStream,
    MessageBatch,
    StreamMessage,
    make_consumer,
)
from pinot_tpu.realtime.mutable import MutableSegment
from pinot_tpu.realtime.manager import (
    RealtimeSegmentDataManager,
    RealtimeTableDataManager,
)

__all__ = [
    "FileStream",
    "InMemoryStream",
    "MessageBatch",
    "StreamMessage",
    "make_consumer",
    "MutableSegment",
    "RealtimeSegmentDataManager",
    "RealtimeTableDataManager",
]
