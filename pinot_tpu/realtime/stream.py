"""Stream SPI: pluggable partitioned message sources with ordered offsets.

Reference parity: pinot-spi/.../spi/stream/ — StreamConsumerFactory,
PartitionGroupConsumer.fetchMessages, MessageBatch, and the ordering-abstract
StreamPartitionMsgOffset.  Re-design: offsets are plain ints (the Kafka
LongMsgOffset case); the SPI stays ordering-abstract through compare-by-int.
Kafka/Kinesis/Pulsar bindings are out-of-image (zero egress); the two built-in
consumers — an in-memory topic for tests/simulation and a JSONL file tail —
exercise the same consume loop the reference drives against Kafka.
"""
from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from pinot_tpu.spi.config import StreamConfig
from pinot_tpu.utils.hashing import partition_of


@dataclass
class StreamMessage:
    """One event: optional key (upsert/partition routing), dict payload, and
    the offset AFTER this message (next fetch position)."""

    value: Dict[str, Any]
    offset: int
    key: Optional[Any] = None


@dataclass
class MessageBatch:
    """fetchMessages result (MessageBatch analog): messages plus the offset to
    resume from (offsetOfNextBatch) and end-of-partition flag."""

    messages: List[StreamMessage]
    next_offset: int
    end_of_partition: bool = False

    def __len__(self) -> int:
        return len(self.messages)


class PartitionGroupConsumer:
    """Per-partition consumer contract (PartitionGroupConsumer analog)."""

    def fetch(self, start_offset: int, max_messages: int = 1024) -> MessageBatch:
        raise NotImplementedError

    def latest_offset(self) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStream:
    """A partitioned in-memory topic; the test/simulation stream plugin.

    publish() appends to a partition's log; consumers fetch by offset.  The
    log is append-only so any offset may be re-read (replay after restart —
    the property the consume loop's checkpoint/resume depends on)."""

    def __init__(self, num_partitions: int = 1):
        self.num_partitions = num_partitions
        self._logs: List[List[StreamMessage]] = [[] for _ in range(num_partitions)]
        self._lock = threading.Lock()

    def publish(self, value: Dict[str, Any], key: Optional[Any] = None, partition: Optional[int] = None) -> int:
        with self._lock:
            if partition is None:
                # stable hash (utils/hashing.py murmur2, the Kafka default
                # partitioner): Python's hash() is salted per process
                # (PYTHONHASHSEED), so a producer restart would re-route
                # keys and break partition-affinity invariants (upsert
                # locality, checkpointed offsets pointing at the wrong log)
                partition = partition_of(key, self.num_partitions) if key is not None else 0
            log = self._logs[partition]
            msg = StreamMessage(value=value, offset=len(log) + 1, key=key)
            log.append(msg)
            return msg.offset - 1

    def publish_many(self, values: List[Dict[str, Any]], partition: int = 0) -> None:
        for v in values:
            self.publish(v, partition=partition)

    def consumer(self, partition: int) -> "_MemoryConsumer":
        return _MemoryConsumer(self, partition)


class _MemoryConsumer(PartitionGroupConsumer):
    def __init__(self, stream: InMemoryStream, partition: int):
        self._stream = stream
        self._partition = partition

    def fetch(self, start_offset: int, max_messages: int = 1024) -> MessageBatch:
        with self._stream._lock:
            log = self._stream._logs[self._partition]
            msgs = log[start_offset : start_offset + max_messages]
            next_off = start_offset + len(msgs)
            return MessageBatch(messages=list(msgs), next_offset=next_off, end_of_partition=next_off >= len(log))

    def latest_offset(self) -> int:
        with self._stream._lock:
            return len(self._stream._logs[self._partition])


class FileStream(PartitionGroupConsumer):
    """JSONL file tail: offset = line number.  The batch-file analog of a
    stream partition (reference: pinot-file-ingestion via stream SPI); lines
    appended after open are visible to subsequent fetches.

    The incremental tail (byte-offset memo + torn-tail park) rides the
    shared spi.filesystem.TailFollower — the same follower the standby
    coordinator tails the metadata journal with (cluster/election.py)."""

    def __init__(self, path: str):
        from pinot_tpu.spi.filesystem import TailFollower

        self.path = path
        self._tail = TailFollower(path)

    def fetch(self, start_offset: int, max_messages: int = 1024) -> MessageBatch:
        """Offsets are RAW line indices (blank lines consume an offset but
        emit no message) so fetch/next_offset/latest_offset stay aligned."""
        if not os.path.exists(self.path):
            return MessageBatch(messages=[], next_offset=start_offset, end_of_partition=True)
        lines, next_offset, eof, _truncated = self._tail.read(
            start_line=start_offset,
            max_lines=max_messages,
            count_line=lambda s: bool(s.strip()),
        )
        # a consumer's offset never regresses: a start past EOF (or a file
        # rewritten shorter) reports no progress, not a rewind
        next_offset = max(next_offset, start_offset)
        msgs: List[StreamMessage] = []
        for i, text in lines:
            text = text.strip()
            if text:
                msgs.append(StreamMessage(value=json.loads(text), offset=i))
        return MessageBatch(messages=msgs, next_offset=next_offset, end_of_partition=eof)

    def latest_offset(self) -> int:
        if not os.path.exists(self.path):
            return 0
        with open(self.path, "r", encoding="utf-8") as f:
            return sum(1 for _ in f)


# consumer-factory registry (StreamConsumerFactoryProvider analog)
_FACTORIES: Dict[str, Any] = {}


def register_stream_factory(stream_type: str, factory) -> None:
    _FACTORIES[stream_type] = factory


def make_consumer(cfg: StreamConfig, partition: int, stream: Optional[InMemoryStream] = None) -> PartitionGroupConsumer:
    """StreamConsumerFactory.createPartitionGroupConsumer analog."""
    if cfg.stream_type == "memory":
        if stream is None:
            raise ValueError("memory stream requires the InMemoryStream instance (topic object)")
        return stream.consumer(partition)
    if cfg.stream_type == "file":
        path = cfg.properties.get("path") or cfg.topic
        return FileStream(path)
    if cfg.stream_type in _FACTORIES:
        return _FACTORIES[cfg.stream_type](cfg, partition)
    raise ValueError(f"unknown stream type {cfg.stream_type!r} (register via register_stream_factory)")
