"""Mutable (consuming) segment: growable columns + append dictionaries.

Reference parity: pinot-segment-local MutableSegmentImpl.index
(MutableSegmentImpl.java:638) — per-row ingest into growable forward indexes
and insertion-order dictionaries, queryable while consuming.

Re-design (TPU-first): the reference serves queries directly off mutating
per-row structures; a TPU kernel needs dense arrays and static shapes.  So
ingest appends O(1) into host buffers (string-like and dictionary columns
through an *unsorted append dictionary* — value->code hash map, values in
insertion order), and the query path materializes a cheap columnar
*snapshot* — an ImmutableSegment built vectorized over the buffered rows,
cached by row count.  Snapshot builds skip the heavyweight indexes (bitmap /
star-tree) and segment sorting; the sealed build (seal()) runs the full
configured pipeline.  This is the mutable/immutable split the reference gets
by swapping MutableSegmentImpl for ImmutableSegmentImpl at commit time
(RealtimeSegmentDataManager.java:933), with the extra step that *every*
snapshot is already in the immutable (device-friendly) layout.
"""
from __future__ import annotations

import threading
import time
from dataclasses import replace
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.realtime.upsert import _as_elems
from pinot_tpu.segment.builder import build_segment
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import DataType, FieldRole, Schema


class AppendDictionary:
    """Unsorted insertion-order dictionary (MutableDictionary analog).

    index() returns a stable code per distinct value in O(1); codes are
    remapped to the sorted immutable dictionary at snapshot/seal time."""

    __slots__ = ("values", "_codes")

    def __init__(self) -> None:
        self.values: List[Any] = []
        self._codes: Dict[Any, int] = {}

    def index(self, value: Any) -> int:
        code = self._codes.get(value)
        if code is None:
            code = len(self.values)
            self._codes[value] = code
            self.values.append(value)
        return code

    def indexOf(self, value: Any) -> int:
        return self._codes.get(value, -1)

    @property
    def cardinality(self) -> int:
        return len(self.values)


class MutableSegment:
    """Growable columnar segment; queryable through snapshot()."""

    def __init__(
        self,
        schema: Schema,
        name: str,
        table_config: Optional[TableConfig] = None,
        start_offset: int = 0,
    ):
        self.schema = schema
        self.name = name
        self.config = table_config or TableConfig(name=schema.name)
        self.start_offset = start_offset
        self.creation_time_ms = int(time.time() * 1000)
        self._dicts: Dict[str, AppendDictionary] = {}
        self._buffers: Dict[str, List[Any]] = {}
        self._null_counts: Dict[str, int] = {}
        self._mv: set = set()
        for f in schema.fields:
            if not f.single_value:
                # MV realtime (round 5, VERDICT r4 #10): buffers hold tuples
                # of coerced elements; NULL/missing ingests as the empty
                # tuple (Pinot's MV default) — MutableSegmentImpl.java:638
                # wires the same per-row MV forward index
                self._mv.add(f.name)
            self._buffers[f.name] = []
            self._null_counts[f.name] = 0
            if f.data_type.is_string_like and f.name not in self._mv:
                # MV strings buffer decoded tuples directly (no append dict)
                self._dicts[f.name] = AppendDictionary()
        self._num_docs = 0
        self._snapshot: Optional[ImmutableSegment] = None
        self._snapshot_docs = -1
        # guards buffers/dicts against a threaded consumer (run_forever)
        # racing snapshot()/seal() readers — one writer, cheap lock
        self._lock = threading.RLock()

    # -- ingest ----------------------------------------------------------
    def index(self, row: Dict[str, Any]) -> int:
        """Ingest one decoded row; returns its docId (MutableSegmentImpl.index).

        The record pipeline (type coercion + null substitution) runs here so
        buffers always hold schema-typed values."""
        with self._lock:
            return self._index_locked(row)

    def _index_locked(self, row: Dict[str, Any]) -> int:
        for f in self.schema.fields:
            v = row.get(f.name)
            buf = self._buffers[f.name]
            if f.name in self._mv:
                buf.append(tuple(_coerce(f.data_type, e) for e in _as_elems(v)))
                continue
            if v is None or (isinstance(v, float) and np.isnan(v)):
                if not f.nullable:
                    v = f.data_type.null_placeholder
                    if f.data_type.is_string_like:
                        buf.append(self._dicts[f.name].index(v))
                        continue
                    buf.append(v)
                    continue
                self._null_counts[f.name] += 1
                buf.append(None)
                continue
            d = self._dicts.get(f.name)
            if d is not None:
                buf.append(d.index(_coerce(f.data_type, v)))
            else:
                buf.append(_coerce(f.data_type, v))
        self._num_docs += 1
        return self._num_docs - 1

    def index_batch(self, rows: List[Dict[str, Any]]) -> None:
        for r in rows:
            self.index(r)

    @property
    def num_docs(self) -> int:
        with self._lock:
            return self._num_docs

    def value_at(self, column: str, doc_id: int) -> Any:
        """Point read of one ingested value (upsert comparison reads)."""
        with self._lock:
            v = self._buffers[column][doc_id]
            if column in self._mv:
                return v  # tuple of coerced elements
            d = self._dicts.get(column)
            if v is None or d is None:
                return v
            return d.values[v]

    # -- query facade ----------------------------------------------------
    def column_values(self, column: str) -> np.ndarray:
        """Materialize one column (insertion order) as an object/typed array."""
        with self._lock:
            return self._column_values_locked(column)

    def _column_values_locked(self, column: str) -> np.ndarray:
        f = self.schema.field(column)
        buf = self._buffers[column]
        if column in self._mv:
            out = np.empty(len(buf), dtype=object)
            for i, t in enumerate(buf):
                out[i] = t
            return out
        d = self._dicts.get(column)
        if d is not None:
            vals = np.asarray(d.values, dtype=object)
            out = np.empty(len(buf), dtype=object)
            codes = np.array([c if c is not None else -1 for c in buf], dtype=np.int64)
            ok = codes >= 0
            out[ok] = vals[codes[ok]]
            out[~ok] = None
            return out
        if self._null_counts[column]:
            return np.asarray(buf, dtype=object)
        return np.asarray(buf, dtype=f.data_type.np_dtype)

    def snapshot(self) -> ImmutableSegment:
        """Columnar view of all rows ingested so far, cached by row count.

        Round 5 (VERDICT r4 #10): snapshots now build the table's configured
        inverted/range/bloom/json/text/vector indexes too — consuming-
        segment queries take the same index-accelerated paths as sealed ones
        (RealtimeLuceneTextIndex / realtime inverted-index analog; the
        reference maintains them incrementally, we rebuild per snapshot,
        amortized by the row-count cache).  Rows keep INSERTION ORDER (no
        segment sort — upsert validDocIds reference snapshot docids) and
        star-trees stay seal-only."""
        with self._lock:
            if self._snapshot is not None and self._snapshot_docs == self._num_docs:
                return self._snapshot
            idx = self.config.indexing
            snap_cfg = replace(
                self.config,
                indexing=replace(idx, sorted_column=None, star_tree_index_configs=[]),
            )
            data = {f.name: self.column_values(f.name) for f in self.schema.fields}
            seg = build_segment(self.schema, data, self.name, snap_cfg)
            seg.in_memory = True  # consuming segments are not yet durable
            self._snapshot = seg
            self._snapshot_docs = self._num_docs
            return seg

    # -- seal ------------------------------------------------------------
    def seal(self, output_dir: Optional[str] = None) -> ImmutableSegment:
        """Final immutable build with the table's FULL indexing config
        (segment sort, bitmap indexes, star-trees) — the build the reference
        runs in RealtimeSegmentDataManager.buildSegmentInternal."""
        with self._lock:
            data = {f.name: self.column_values(f.name) for f in self.schema.fields}
            return build_segment(self.schema, data, self.name, self.config, output_dir=output_dir)


def _coerce(dt: DataType, v: Any):
    if dt is DataType.STRING or dt is DataType.JSON:
        return v if isinstance(v, str) else str(v)
    if dt is DataType.BYTES:
        return v if isinstance(v, bytes) else bytes(v)
    if dt in (DataType.INT, DataType.LONG, DataType.TIMESTAMP):
        return int(v)
    if dt is DataType.BOOLEAN:
        return int(bool(v))
    return float(v)
