"""Upsert + dedup metadata: PK -> latest location, validDocIds bitmasks.

Reference parity: pinot-segment-local ConcurrentMapPartitionUpsertMetadataManager
(addOrReplaceSegment / addRecord :71-115 — PK hash map holding the winning
(segment, docId, comparisonValue); losing rows cleared from their segment's
validDocIds bitmap) and PartitionDedupMetadataManager (drop-duplicate-PK).

Re-design: validDocIds is a host numpy bool mask per segment, shipped to the
device as a filter param (query/planner.py "__valid__") and ANDed into every
predicate — the TPU form of the reference's MutableRoaringBitmap intersected
in FilterPlanNode.  Comparison defaults to the table's time column; later
arrival wins ties (>=), matching the reference.  On restart the map is
bootstrapped by replaying sealed segments in sequence order
(addOrReplaceSegment's rebuild path) — no separate snapshot file needed.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import Schema


def _as_elems(v) -> Tuple:
    """Normalize a value to MV elements: None -> (), scalar -> (v,)."""
    if v is None:
        return ()
    if isinstance(v, (list, tuple, np.ndarray)):
        return tuple(v)
    return (v,)


class _Location:
    __slots__ = ("segment", "doc", "cmp", "deleted")

    def __init__(self, segment: str, doc: int, cmp: Any, deleted: bool = False):
        self.segment = segment
        self.doc = doc
        self.cmp = cmp
        self.deleted = deleted


class PartitionUpsertMetadataManager:
    """FULL upsert: latest row per primary key wins; older rows are masked
    out of their segment's validDocIds."""

    def __init__(self, schema: Schema, config: TableConfig):
        if not schema.primary_key_columns:
            raise ValueError(f"upsert table {config.name} needs primaryKeyColumns in the schema")
        self.schema = schema
        self.config = config
        self.pk_cols = list(schema.primary_key_columns)
        cc = (config.upsert.comparison_column if config.upsert else None) or config.segments.time_column
        if not cc:
            raise ValueError(
                "upsert requires a comparison column (upsertConfig.comparisonColumn or the table time column)"
            )
        self.cmp_col = cc
        # pk tuple -> winning location; valid masks by segment name.
        self.pk_map: Dict[Tuple, _Location] = {}
        self.valid: Dict[str, Any] = {}  # list[bool] (consuming) | np.ndarray (sealed)
        self._strategies = {
            k.lower(): v.upper()
            for k, v in (config.upsert.partial_upsert_strategies if config.upsert else {}).items()
        }
        up = config.upsert
        # metadataTTL: keys whose comparison value trails the watermark by
        # more than this stop being tracked (reference
        # ConcurrentMapPartitionUpsertMetadataManager.java:49); their rows
        # stay valid — only dedup/replace tracking ends, as in the reference
        self.metadata_ttl = float(getattr(up, "metadata_ttl", 0.0) or 0.0) if up else 0.0
        self.delete_col = getattr(up, "delete_record_column", None) if up else None
        self._cmp_watermark: Optional[float] = None
        self._adds_since_expiry = 0

    # -- metadataTTL -----------------------------------------------------
    def _note_watermark(self, cmp: Any) -> None:
        if self.metadata_ttl <= 0:
            return
        try:
            c = float(cmp)
        except (TypeError, ValueError):
            return
        if self._cmp_watermark is None or c > self._cmp_watermark:
            self._cmp_watermark = c
        self._adds_since_expiry += 1
        if self._adds_since_expiry >= 1024:
            self.expire_ttl_keys()

    def expire_ttl_keys(self) -> None:
        """Drop pk_map entries older than (watermark - metadataTTL).  Their
        rows remain visible (valid masks untouched) except expired DELETE
        tombstones, which simply stop rejecting older arrivals."""
        self._adds_since_expiry = 0
        if self.metadata_ttl <= 0 or self._cmp_watermark is None:
            return
        floor = self._cmp_watermark - self.metadata_ttl
        dead = []
        for pk, loc in self.pk_map.items():
            try:
                if float(loc.cmp) < floor:
                    dead.append(pk)
            except (TypeError, ValueError):
                continue
        for pk in dead:
            del self.pk_map[pk]

    # -- helpers ---------------------------------------------------------
    def _pk_of(self, row: Dict[str, Any]) -> Tuple:
        return tuple(row.get(c) for c in self.pk_cols)

    def _resolve(self, pk: Tuple, cand: _Location) -> None:
        """addRecord: candidate vs incumbent; later arrival wins ties."""
        cur = self.pk_map.get(pk)
        if cur is None:
            self.pk_map[pk] = cand
            return
        if cand.cmp >= cur.cmp:
            self._invalidate(cur)
            self.pk_map[pk] = cand
        else:
            self._invalidate(cand)

    def _invalidate(self, loc: _Location) -> None:
        if loc.doc < 0:  # compacted-away doc (delete tombstone): nothing to mask
            return
        mask = self.valid.get(loc.segment)
        if mask is not None:
            mask[loc.doc] = False

    # -- consume-loop hooks (RealtimeTableDataManager calls these) -------
    def track_consuming(self, name: str) -> None:
        self.valid.setdefault(name, [])

    def on_indexed(self, mgr, msg, doc_id: int) -> None:
        name = mgr.mutable.name
        self.track_consuming(name)
        self.valid[name].append(True)
        row = msg.value
        cmp = row.get(self.cmp_col)
        self._note_watermark(cmp)
        deleted = bool(self.delete_col and row.get(self.delete_col))
        loc = _Location(name, doc_id, cmp, deleted=deleted)
        self._resolve(self._pk_of(row), loc)
        if deleted and self.pk_map.get(self._pk_of(row)) is loc:
            # consistent delete: the winning tombstone hides its own row too;
            # it stays in pk_map (rejecting older arrivals) until TTL expiry
            self._invalidate(loc)

    def on_seal(self, mgr, sealed: ImmutableSegment) -> None:
        """Freeze the consuming mask into the sealed segment, remapping
        through the builder's sort permutation when the build reordered rows."""
        name = sealed.name
        mask = np.asarray(self.valid.get(name, []), dtype=bool)
        if len(mask) != sealed.num_docs:
            mask = np.ones(sealed.num_docs, dtype=bool)
        order = sealed.sort_order
        if order is not None:
            mask = mask[order]  # new position p holds input row order[p]
            inverse = np.empty_like(order)
            inverse[order] = np.arange(len(order))
            for loc in self.pk_map.values():
                if loc.segment == name:
                    loc.doc = int(inverse[loc.doc])
        self.valid[name] = mask
        sealed.valid_docs = mask  # shared reference: later invalidations apply

    def on_rolled(self, mgr) -> None:
        self.track_consuming(mgr.mutable.name)

    # -- PARTIAL upsert ---------------------------------------------------
    def transform_row(self, table_mgr, mgr, msg) -> Dict[str, Any]:
        """PARTIAL mode: merge the incoming row with the current winning row
        per column strategy (PartialUpsertHandler analog).  Strategies:
        OVERWRITE (default; incoming None keeps old), IGNORE (keep old),
        INCREMENT (old + new), APPEND (old MV elements + new), UNION
        (order-preserving MV set union)."""
        row = msg.value
        if (self.config.upsert.mode or "").upper() != "PARTIAL":
            return row
        cur = self.pk_map.get(self._pk_of(row))
        if cur is None or cur.deleted:  # deleted PK: merge against nothing
            return row
        old = self._read_row(table_mgr, cur)
        if old is None:
            return row
        merged: Dict[str, Any] = {}
        strategies = self._strategies
        for f in self.schema.fields:
            name = f.name
            strat = strategies.get(name.lower(), "OVERWRITE")
            new_v, old_v = row.get(name), old.get(name)
            if name in self.pk_cols or name == self.cmp_col:
                merged[name] = new_v
            elif strat == "IGNORE":
                merged[name] = old_v
            elif strat == "INCREMENT":
                merged[name] = (old_v or 0) + (new_v or 0)
            elif strat == "APPEND":
                # MV realtime (round 5): concatenate old + incoming elements
                merged[name] = tuple(_as_elems(old_v)) + tuple(_as_elems(new_v))
            elif strat == "UNION":
                out = list(_as_elems(old_v))
                for e in _as_elems(new_v):
                    if e not in out:
                        out.append(e)
                merged[name] = tuple(out)
            else:  # OVERWRITE
                merged[name] = new_v if new_v is not None else old_v
        return merged

    def _read_row(self, table_mgr, loc: _Location) -> Optional[Dict[str, Any]]:
        """Point-read the winning row's values at its current location."""
        if loc.doc < 0:  # compacted-away (tombstone): no row to read
            return None
        for mgr in table_mgr.managers.values():
            if mgr.mutable.name == loc.segment:
                return {f.name: mgr.mutable.value_at(f.name, loc.doc) for f in self.schema.fields}
        for segs in table_mgr.sealed.values():
            for seg in segs:
                if seg.name == loc.segment:
                    # point reads, NOT full-column decodes (O(1) per field)
                    return {f.name: seg.column(f.name).value_at(loc.doc) for f in self.schema.fields}
        return None

    # -- query-time ------------------------------------------------------
    def attach_snapshot_mask(self, snapshot: ImmutableSegment, name: str) -> None:
        """Consuming snapshots get a frozen copy of the live mask (the list
        keeps growing; the snapshot covers a row-count prefix)."""
        mask = self.valid.get(name)
        if mask is None:
            return
        snapshot.valid_docs = np.asarray(mask[: snapshot.num_docs], dtype=bool)

    # -- restart ---------------------------------------------------------
    def bootstrap(self, sealed_in_order: List[ImmutableSegment]) -> None:
        """Rebuild pk_map + validDocIds by replaying sealed segments in
        sequence order (the reference's addOrReplaceSegment path)."""
        for seg in sealed_in_order:
            n = seg.num_docs
            self.valid[seg.name] = np.ones(n, dtype=bool)
            seg.valid_docs = self.valid[seg.name]
            pk_vals = [seg.column(c).decoded() for c in self.pk_cols]
            cmp_vals = seg.column(self.cmp_col).decoded()
            del_vals = (
                seg.column(self.delete_col).decoded()
                if self.delete_col and self.delete_col in seg.columns
                else None
            )
            for doc in range(n):
                pk = tuple(v[doc].item() if isinstance(v[doc], np.generic) else v[doc] for v in pk_vals)
                cmp = cmp_vals[doc]
                cmp = cmp.item() if isinstance(cmp, np.generic) else cmp
                self._note_watermark(cmp)
                deleted = bool(del_vals[doc]) if del_vals is not None else False
                loc = _Location(seg.name, doc, cmp, deleted=deleted)
                self._resolve(pk, loc)
                if deleted and self.pk_map.get(pk) is loc:
                    self._invalidate(loc)


class PartitionDedupMetadataManager:
    """Dedup: the FIRST row per primary key is kept; later duplicates are
    dropped before indexing (PartitionDedupMetadataManager analog)."""

    def __init__(self, schema: Schema, config: TableConfig):
        if not schema.primary_key_columns:
            raise ValueError(f"dedup table {config.name} needs primaryKeyColumns in the schema")
        self.pk_cols = list(schema.primary_key_columns)
        self.seen: set = set()

    def _pk_of(self, row: Dict[str, Any]) -> Tuple:
        return tuple(row.get(c) for c in self.pk_cols)

    def should_index(self, mgr, msg) -> bool:
        pk = self._pk_of(msg.value)
        if pk in self.seen:
            return False
        self.seen.add(pk)
        return True

    def bootstrap(self, sealed_in_order: List[ImmutableSegment]) -> None:
        for seg in sealed_in_order:
            pk_vals = [seg.column(c).decoded() for c in self.pk_cols]
            for doc in range(seg.num_docs):
                self.seen.add(
                    tuple(v[doc].item() if isinstance(v[doc], np.generic) else v[doc] for v in pk_vals)
                )
