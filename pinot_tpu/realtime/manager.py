"""Realtime consumption: per-partition consume loop + seal/swap + resume.

Reference parity: RealtimeSegmentDataManager (pinot-core/.../data/manager/
realtime/RealtimeSegmentDataManager.java — consumeLoop :470, fetch :492,
processStreamEvents :591, end-criteria checks, commitSegment :971) and
RealtimeTableDataManager (.../realtime/RealtimeTableDataManager.java:97).

Re-design: the reference runs one consumer thread per partition with a
controller-driven commit FSM; here consumption is *step-driven* —
`consume()` pulls batches until caught up or a segment seals — so tests and
embedding hosts control interleaving deterministically, and a thread driver
(`run_forever`) is a loop around the same step.  The commit protocol
collapses to: seal -> durable immutable build -> atomic swap into the table
view -> checkpoint {offset, seq} fsynced to disk.  Restart replays from the
last committed offset: consuming-segment rows are intentionally dropped and
re-consumed (exactly the reference's recovery semantics — uncommitted rows
live only in the mutable segment).
"""
from __future__ import annotations

import copy
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from pinot_tpu.realtime.mutable import MutableSegment
from pinot_tpu.realtime.stream import InMemoryStream, PartitionGroupConsumer, make_consumer
from pinot_tpu.segment.segment import ImmutableSegment
from pinot_tpu.segment.store import SegmentCorruptError
from pinot_tpu.spi.config import TableConfig
from pinot_tpu.spi.schema import Schema
from pinot_tpu.utils.crashpoints import crash_point
from pinot_tpu.utils.metrics import METRICS

log = logging.getLogger("pinot_tpu.realtime")


def segment_name(table: str, partition: int, seq: int) -> str:
    """LLCSegmentName analog: table__partition__sequence."""
    return f"{table}__{partition}__{seq}"


class RealtimeSegmentDataManager:
    """Owns one partition's consuming segment + its consume loop."""

    def __init__(
        self,
        table: "RealtimeTableDataManager",
        partition: int,
        consumer: PartitionGroupConsumer,
        start_offset: int = 0,
        seq: int = 0,
    ):
        self.table = table
        self.partition = partition
        self.consumer = consumer
        self.offset = start_offset
        self.seq = seq
        # monotonic: segment age (seal criteria) is an elapsed-time measure
        self.segment_start_ms = time.monotonic() * 1000
        self.mutable = MutableSegment(
            table.schema,
            segment_name(table.config.name, partition, seq),
            table.config,
            start_offset=start_offset,
        )

    # -- consume loop ----------------------------------------------------
    def consume(self, max_batches: Optional[int] = None, batch_size: int = 1024) -> int:
        """Pull batches until caught up, a segment seals, or max_batches.
        Returns rows ingested (consumeLoop + processStreamEvents analog)."""
        ingested = 0
        batches = 0
        while max_batches is None or batches < max_batches:
            batch = self.consumer.fetch(self.offset, batch_size)
            batches += 1
            sealed = False
            for msg in batch.messages:
                if not self.table._should_index(self, msg):
                    self.offset = msg.offset
                    continue
                row = self.table._transform_row(self, msg)
                doc_id = self.mutable.index(row)
                self.table._on_indexed(self, msg, doc_id)
                self.offset = msg.offset
                ingested += 1
                # per-row end-criteria check: segments seal at EXACTLY the
                # configured row cap (the reference's canTakeMore guard),
                # mid-batch if needed; the tail of the batch re-fetches into
                # the rolled segment on the next loop iteration.
                if self._end_criteria_reached():
                    self.seal_and_swap()
                    sealed = True
                    break
            if sealed:
                break
            self.offset = batch.next_offset
            # empty batch = caught up, even if the partition never "ends"
            # (Kafka-like live streams); without this, max_batches=None spins
            if batch.end_of_partition or not batch.messages:
                break
        return ingested

    def _end_criteria_reached(self) -> bool:
        cfg = self.table.config.stream
        if cfg is None:
            return False
        if self.mutable.num_docs >= cfg.max_rows_per_segment:
            return True
        age_s = (time.monotonic() * 1000 - self.segment_start_ms) / 1000
        return self.mutable.num_docs > 0 and age_s >= cfg.max_segment_seconds

    # -- commit ----------------------------------------------------------
    def seal_and_swap(self) -> ImmutableSegment:
        """End-of-segment commit: durable build, swap, checkpoint, roll.

        Order matters (crash safety): the immutable segment hits disk BEFORE
        the checkpoint advances, so a crash between the two replays into a
        duplicate *file* (overwritten on rebuild), never into lost rows."""
        sealed = self.mutable.seal(output_dir=self.table.segment_dir(self.mutable.name))
        crash_point("segment.seal.after_build")
        # deep-store copy BEFORE the checkpoint references the segment as
        # committed: once {offset, seq} advances, the segment must survive
        # the loss of this host's data dir (segment completion protocol)
        if self.table.deep_store is not None:
            self.table.deep_store.put_segment(self.table.config.name, sealed)
        crash_point("segment.seal.after_upload")
        self.table._swap_in(self.partition, sealed)
        crash_point("segment.seal.after_swap")
        self.seq += 1
        self.table._commit_checkpoint(self.partition, self.offset, self.seq)
        self.segment_start_ms = time.monotonic() * 1000
        self.mutable = MutableSegment(
            self.table.schema,
            segment_name(self.table.config.name, self.partition, self.seq),
            self.table.config,
            start_offset=self.offset,
        )
        self.table._on_rolled(self)
        return sealed

    def run_forever(self, poll_interval_s: float = 0.05, stop_event: Optional[threading.Event] = None) -> None:
        """Thread driver: the reference's PartitionConsumer thread."""
        while stop_event is None or not stop_event.is_set():
            n = self.consume(max_batches=4)
            if n == 0:
                time.sleep(poll_interval_s)


class RealtimeTableDataManager:
    """All partitions of one realtime table: sealed + consuming segments.

    data_dir layout:
      {data_dir}/{segment_name}/...   - sealed immutable segments
      {data_dir}/checkpoint.json      - {partition: {offset, seq, segments}}
    """

    def __init__(
        self,
        schema: Schema,
        config: TableConfig,
        data_dir: str,
        stream: Optional[InMemoryStream] = None,
        num_partitions: Optional[int] = None,
        deep_store=None,
    ):
        if config.stream is None:
            raise ValueError(f"table {config.name} has no streamConfigs")
        self.schema = schema
        self.config = config
        self.data_dir = data_dir
        self.stream = stream
        # segment deep store (cluster/deepstore.py): sealed segments are
        # uploaded at commit time and corrupt local copies re-download
        self.deep_store = deep_store
        # checkpoint-committed hook: fn(partition, offset, seq), called
        # AFTER the fsync'd commit — the coordinator journals the pointer
        self.on_checkpoint = None
        os.makedirs(data_dir, exist_ok=True)
        if num_partitions is None:
            num_partitions = stream.num_partitions if stream is not None else 1
        self.num_partitions = num_partitions
        self.sealed: Dict[int, List[ImmutableSegment]] = {p: [] for p in range(num_partitions)}
        self.managers: Dict[int, RealtimeSegmentDataManager] = {}
        self._checkpoint = self._load_checkpoint()
        self._lock = threading.Lock()
        for p in range(num_partitions):
            self._recover_partition(p)
            cp = self._checkpoint.get(str(p), {"offset": 0, "seq": 0})
            consumer = make_consumer(config.stream, p, stream=stream)
            self.managers[p] = RealtimeSegmentDataManager(
                self, p, consumer, start_offset=cp["offset"], seq=cp["seq"]
            )
        # upsert / dedup metadata (realtime/upsert.py), bootstrapped by
        # replaying recovered sealed segments in (partition, seq) order
        self.upsert = None
        self.dedup = None
        recovered = [s for p in range(num_partitions) for s in self.sealed[p]]
        if config.upsert is not None and config.upsert.mode != "NONE":
            from pinot_tpu.realtime.upsert import PartitionUpsertMetadataManager

            self.upsert = PartitionUpsertMetadataManager(schema, config)
            self.upsert.bootstrap(recovered)
            for mgr in self.managers.values():
                self.upsert.track_consuming(mgr.mutable.name)
        if config.dedup is not None and config.dedup.enabled:
            from pinot_tpu.realtime.upsert import PartitionDedupMetadataManager

            self.dedup = PartitionDedupMetadataManager(schema, config)
            self.dedup.bootstrap(recovered)

    # -- durability ------------------------------------------------------
    def segment_dir(self, name: str) -> str:
        return os.path.join(self.data_dir, name)

    def _checkpoint_path(self) -> str:
        return os.path.join(self.data_dir, "checkpoint.json")

    def _load_checkpoint(self) -> Dict[str, Any]:
        """Load the committed checkpoint, tolerating the artifacts a crash
        can leave: stale *.tmp files are swept; a corrupt checkpoint.json is
        quarantined aside (evidence, not deleted) and the previous committed
        state (checkpoint.json.bak) — or empty — is recovered instead.
        Recovery from an older checkpoint is safe by construction: offsets
        only re-consume, and sealed-segment files overwrite idempotently."""
        from pinot_tpu.spi.filesystem import sweep_tmp

        sweep_tmp(self.data_dir)
        path = self._checkpoint_path()
        for candidate in (path, path + ".bak"):
            if not os.path.exists(candidate):
                continue
            try:
                with open(candidate, "r", encoding="utf-8") as f:
                    return json.load(f)
            except (json.JSONDecodeError, OSError, ValueError) as e:
                METRICS.counter("realtime.checkpointCorrupt").inc()
                aside = candidate + ".corrupt"
                try:
                    if os.path.exists(aside):
                        os.remove(aside)
                    os.replace(candidate, aside)
                except OSError:
                    aside = None
                log.warning(
                    "corrupt realtime checkpoint %s (%s) quarantined to %s; "
                    "recovering from previous state", candidate, e, aside,
                )
        return {}

    def _commit_checkpoint(self, partition: int, offset: int, seq: int) -> None:
        """Advance one partition's committed {offset, seq, segments} pointer.

        The shared checkpoint dict is mutated AND deep-copied under _lock —
        a concurrent partition's commit can neither interleave a half-updated
        entry into this dump nor mutate a list while json serializes it (the
        race the old code had by dumping the live dict outside the lock).
        The dump itself runs on the copy, outside the lock."""
        with self._lock:
            cp = self._checkpoint.setdefault(str(partition), {"offset": 0, "seq": 0, "segments": []})
            cp["offset"] = offset
            cp["seq"] = seq
            cp["segments"] = [s.name for s in self.sealed[partition]]
            snapshot = copy.deepcopy(self._checkpoint)
        path = self._checkpoint_path()
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snapshot, f)
            crash_point("realtime.checkpoint.after_write")
            f.flush()
            os.fsync(f.fileno())
        # keep the last committed checkpoint as the corruption fallback
        if os.path.exists(path):
            bak = path + ".bak"
            try:
                os.replace(path, bak)
            except OSError:
                pass
        crash_point("realtime.checkpoint.after_bak")
        os.replace(tmp, path)
        crash_point("realtime.checkpoint.after_replace")
        from pinot_tpu.spi.filesystem import fsync_dir

        fsync_dir(self.data_dir)
        if self.on_checkpoint is not None:
            self.on_checkpoint(partition, offset, seq)

    def _recover_partition(self, partition: int) -> None:
        """Reload committed sealed segments from disk (restart path),
        CRC-verifying each; a missing/corrupt local copy re-downloads from
        the deep store (it was uploaded before the checkpoint committed)."""
        cp = self._checkpoint.get(str(partition))
        if not cp:
            return
        table_name = self.config.name
        for name in cp.get("segments", []):
            path = self.segment_dir(name)
            seg = None
            try:
                if os.path.isdir(path):
                    seg = ImmutableSegment.load(path, verify=True)
            except SegmentCorruptError as e:
                METRICS.counter("realtime.segmentsCorrupt").inc()
                aside = path + ".corrupt"
                import shutil

                shutil.rmtree(aside, ignore_errors=True)
                os.replace(path, aside)
                log.warning("quarantined corrupt sealed segment %s (%s)", path, e)
            if seg is None and self.deep_store is not None and self.deep_store.has_segment(table_name, name):
                seg = self.deep_store.fetch_segment(table_name, name, self.data_dir)
                METRICS.counter("realtime.segmentsRestored").inc()
            if seg is not None:
                self.sealed[partition].append(seg)
            else:
                METRICS.counter("realtime.segmentsUnrecoverable").inc()
                log.error(
                    "committed sealed segment %s/%s is in neither the data dir "
                    "nor the deep store", table_name, name,
                )

    # -- swap/roll hooks -------------------------------------------------
    def _swap_in(self, partition: int, sealed: ImmutableSegment) -> None:
        with self._lock:
            self.sealed[partition].append(sealed)
        if self.upsert is not None:
            self.upsert.on_seal(self.managers.get(partition), sealed)

    def _should_index(self, mgr: RealtimeSegmentDataManager, msg) -> bool:
        if self.dedup is not None:
            return self.dedup.should_index(mgr, msg)
        return True

    def _transform_row(self, mgr: RealtimeSegmentDataManager, msg) -> Dict[str, Any]:
        """Record-transform hook: PARTIAL upsert merges the incoming row
        with the current winning row before indexing."""
        if self.upsert is not None:
            return self.upsert.transform_row(self, mgr, msg)
        return msg.value

    def _on_indexed(self, mgr: RealtimeSegmentDataManager, msg, doc_id: int) -> None:
        if self.upsert is not None:
            self.upsert.on_indexed(mgr, msg, doc_id)

    def _on_rolled(self, mgr: RealtimeSegmentDataManager) -> None:
        if self.upsert is not None:
            self.upsert.on_rolled(mgr)

    # -- consumption driver ----------------------------------------------
    def consume_all(self, max_batches: Optional[int] = None) -> int:
        """Step every partition's consumer (test/simulation driver)."""
        total = 0
        for mgr in self.managers.values():
            while True:
                n = mgr.consume(max_batches=max_batches)
                total += n
                if n == 0 or max_batches is not None:
                    break
        return total

    # -- query view ------------------------------------------------------
    def query_segments(self) -> List[ImmutableSegment]:
        """Sealed segments + a snapshot of each non-empty consuming segment —
        the segment list the broker's routing table would return."""
        out: List[ImmutableSegment] = []
        for p in range(self.num_partitions):
            with self._lock:
                out.extend(self.sealed[p])
            mgr = self.managers.get(p)
            if mgr is not None and mgr.mutable.num_docs > 0:
                snap = mgr.mutable.snapshot()
                if self.upsert is not None:
                    self.upsert.attach_snapshot_mask(snap, mgr.mutable.name)
                out.append(snap)
        return out

    @property
    def total_rows(self) -> int:
        with self._lock:
            sealed_rows = sum(s.num_docs for segs in self.sealed.values() for s in segs)
        return sealed_rows + sum(m.mutable.num_docs for m in self.managers.values())
