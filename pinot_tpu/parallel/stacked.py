"""Stacked (sharded) table: N segments as one leading-axis device array set.

Reference parity: Pinot's intra-server segment parallelism + scatter-gather
(BaseCombineOperator.java:202-218 runs numTasks worker threads over the
segment list; QueryRouter fans out one request per server).  SURVEY.md 2.5
maps both onto ONE TPU-native construct: segments stacked on a leading axis,
sharded over a jax.sharding.Mesh, with the per-segment combine becoming an
in-graph psum over ICI (SURVEY.md section 7 "Combine = collective").

The load-bearing alignment trick: all shards share ONE dictionary per column
(the key space is global), so per-shard dense group tables are element-wise
addable — the combine is literally `lax.psum`.  Pinot pays a keyed hash merge
(IndexedTable) for the same step because its per-segment dictionaries differ.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.segment.dictionary import Dictionary, min_code_dtype
from pinot_tpu.segment.segment import ColumnData, ImmutableSegment
from pinot_tpu.segment.stats import ColumnStats
from pinot_tpu.spi.schema import DataType, FieldRole, Schema


@dataclass
class StackedColumn:
    """Host-side stacked column: row arrays are [num_shards, docs_per_shard]."""

    name: str
    data_type: DataType
    dictionary: Optional[Dictionary]  # GLOBAL dictionary (shared key space)
    codes: Optional[np.ndarray]  # [S, D] unsigned codes (MV: [S, D, max_len])
    values: Optional[np.ndarray]  # [S, D] raw numerics otherwise
    nulls: Optional[np.ndarray]  # [S, D] bool, None if no nulls
    stats: ColumnStats
    # multi-value: [S, D] per-row element counts; padded cells hold the
    # padding code (== cardinality), mirroring segment/builder MV layout
    mv_lengths: Optional[np.ndarray] = None
    # bit-packed forward index (segment/packing.py layout): codes in
    # `code_bits`-wide lanes inside uint32 words, [S, D * code_bits / 32].
    # D is 32-aligned so no word straddles a shard boundary.  None when the
    # cardinality needs >16 bits (stored unpacked) or the column is MV.
    code_bits: Optional[int] = None
    packed: Optional[np.ndarray] = None

    @property
    def is_multi_value(self) -> bool:
        return self.mv_lengths is not None

    @property
    def has_dictionary(self) -> bool:
        return self.dictionary is not None

    @property
    def cardinality(self) -> int:
        return self.dictionary.cardinality if self.dictionary else self.stats.cardinality


def _stack_mv_column(f, raw, n: int, num_shards: int, D: int) -> "StackedColumn":
    """MV column -> [S, D, max_len] padded code matrix + [S, D] lengths
    (distributed twin of segment/builder._build_mv_column)."""
    from pinot_tpu.segment.builder import _build_mv_column

    col = _build_mv_column(f, np.asarray([tuple(v) if v is not None else () for v in raw], dtype=object), n)
    total = num_shards * D
    max_len = col.codes.shape[1]
    codes = np.full((total, max_len), col.dictionary.cardinality, dtype=col.codes.dtype)
    codes[:n] = col.codes
    lengths = np.zeros(total, dtype=np.int32)
    lengths[:n] = col.mv_lengths
    return StackedColumn(
        f.name,
        f.data_type,
        col.dictionary,
        codes.reshape(num_shards, D, max_len),
        None,
        None,
        col.stats,
        mv_lengths=lengths.reshape(num_shards, D),
    )


_BUILD_COUNTER = 0


class StackedTable:
    """A table resident as stacked columns, ready to shard over a device mesh.

    Padding: shards are padded to equal docs_per_shard; `valid[s, d]` marks
    real rows.  Every kernel ANDs `valid` into its filter mask, so padded rows
    are invisible — the static-shape answer to ragged segment sizes
    (SURVEY.md section 7 "Hard parts: dynamic shapes")."""

    def __init__(
        self,
        schema: Schema,
        columns: Dict[str, StackedColumn],
        valid: np.ndarray,  # [S, D] bool
        num_docs: int,
        indexes: Optional[Dict[str, Dict[str, Any]]] = None,
    ):
        self.schema = schema
        self.columns = columns
        self.valid = valid
        self.num_docs = num_docs
        self.num_shards, self.docs_per_shard = valid.shape
        # {"inverted"|"range": {column: index}} over the FLAT PADDED doc
        # space (num_shards * docs_per_shard rows) — docs_per_shard is
        # 32-aligned so per-device bitmap word slices stay word-aligned
        # (query/filter.py shard-aware params)
        self.indexes: Dict[str, Dict[str, Any]] = indexes or {}
        self._device_cache: Dict[Any, Any] = {}
        # guards _device_cache/_group_keys (shared by aliased_view facades);
        # NEVER held across a device copy — staging owners copy lock-free
        # and publish in one critical section
        self._device_lock = threading.Lock()
        # residency cache-group -> the cache keys it charged: one doc-slice
        # of the table is the eviction unit, and ALL its flavors (raw,
        # #packed, valid words, dictionaries it staged) drop together
        self._group_keys: Dict[Any, set] = {}
        # Per-instance nonce in signature(): compiled plans bake ROW-DATA
        # dependent params (sorted doc ranges, index bitmap words), which
        # dictionary fingerprints alone cannot distinguish — two tables with
        # identical shapes/dictionaries but different row content must never
        # share cached plans.
        global _BUILD_COUNTER
        _BUILD_COUNTER += 1
        self._build_nonce = _BUILD_COUNTER

    # -- facade used by FilterCompiler / planner at compile time ---------
    def column(self, name: str) -> StackedColumn:
        try:
            return self.columns[name]
        except KeyError:
            raise KeyError(f"stacked table has no column {name!r}") from None

    @property
    def column_names(self) -> List[str]:
        return list(self.columns)

    def signature(self) -> Tuple:
        """Kernel cache key component: shapes + dictionary fingerprints +
        stats-derived limb plans (baked into fused group-by kernels)."""
        from pinot_tpu.query.planner import column_limb_sig

        parts: List[Tuple] = [(self.num_shards, self.docs_per_shard, self._build_nonce)]
        for name, c in sorted(self.columns.items()):
            parts.append(
                (
                    name,
                    c.dictionary.fingerprint() if c.dictionary else None,
                    str((c.codes if c.codes is not None else c.values).dtype),
                    c.code_bits,  # packed vs unpacked trace different kernels
                    c.nulls is not None,
                    column_limb_sig(c),
                    c.stats.is_sorted,
                    tuple(sorted(k for k, by_col in self.indexes.items() if name in by_col)),
                )
            )
        return tuple(parts)

    # ------------------------------------------------------------------
    @staticmethod
    def build(
        schema: Schema,
        data: Dict[str, np.ndarray],
        num_shards: int,
        no_dictionary_columns: Tuple[str, ...] = (),
        table_config=None,
    ) -> "StackedTable":
        """Build from column-major data, row-partitioned into num_shards.

        table_config.indexing drives index construction (inverted/range
        bitmaps over the flat padded doc space, data pre-sorted when
        sorted_column is declared) — the distributed counterpart of
        segment/builder.py's index creation (SegmentColumnarIndexCreator
        analog), so the shard_map filter kernels can ride bitmap/doc-range
        params instead of code scans."""
        from pinot_tpu.indexes.inverted import InvertedIndex, RangeEncodedIndex
        from pinot_tpu.segment.builder import MAX_BITMAP_INDEX_CARDINALITY, _extract_nulls
        from pinot_tpu.segment.stats import collect_stats

        idx_cfg = table_config.indexing if table_config is not None else None

        names = schema.column_names
        n = len(data[names[0]]) if names else 0
        # 32-align docs_per_shard: per-device row counts stay multiples of 32
        # so index bitmap words split cleanly across devices
        D = -(-n // num_shards)  # ceil
        D = -(-D // 32) * 32
        total = num_shards * D

        # sorted column: physically sort rows (the sorted "index" IS the
        # order, SortedIndexReader analog)
        if idx_cfg is not None and idx_cfg.sorted_column and idx_cfg.sorted_column in data and n > 1:
            order = np.argsort(np.asarray(data[idx_cfg.sorted_column]), kind="stable")
            if not np.array_equal(order, np.arange(n)):
                data = {k: np.asarray(v)[order] for k, v in data.items()}

        valid = np.zeros(total, dtype=bool)
        valid[:n] = True

        columns: Dict[str, StackedColumn] = {}
        indexes: Dict[str, Dict[str, Any]] = {}
        for f in schema.fields:
            if not f.single_value:
                columns[f.name] = _stack_mv_column(f, data[f.name], n, num_shards, D)
                continue
            arr, nmask = _extract_nulls(f, data[f.name])
            no_dict_cfg = tuple(idx_cfg.no_dictionary_columns) if idx_cfg is not None else ()
            use_dict = f.data_type.is_string_like or (
                f.name not in no_dictionary_columns
                and f.name not in no_dict_cfg
                and f.role in (FieldRole.DIMENSION, FieldRole.DATE_TIME)
            )
            padded_nulls = None
            if nmask is not None:
                padded_nulls = np.zeros(total, dtype=bool)
                padded_nulls[:n] = nmask
                padded_nulls = padded_nulls.reshape(num_shards, D)
            if use_dict:
                from pinot_tpu.segment import packing

                dictionary, codes32 = Dictionary.build(f.data_type, arr)
                codes = np.zeros(total, dtype=min_code_dtype(dictionary.cardinality))
                codes[:n] = codes32.astype(codes.dtype)
                stats = collect_stats(f.name, f.data_type, arr, nmask, dictionary.cardinality, True)
                bits = packing.lane_bits(dictionary.cardinality)
                # D is 32-aligned, so packing the flat codes and reshaping
                # never straddles a shard boundary with one word
                packed = (
                    packing.pack_codes(codes, bits).reshape(num_shards, -1)
                    if bits < 32
                    else None
                )
                columns[f.name] = StackedColumn(
                    f.name,
                    f.data_type,
                    dictionary,
                    codes.reshape(num_shards, D),
                    None,
                    padded_nulls,
                    stats,
                    code_bits=bits if bits < 32 else None,
                    packed=packed,
                )
                card = dictionary.cardinality
                if idx_cfg is not None and card <= MAX_BITMAP_INDEX_CARDINALITY:
                    # padded rows carry code 0 and DO enter the bitmaps;
                    # every kernel ANDs the valid mask, so they stay invisible
                    if f.name in idx_cfg.inverted_index_columns:
                        indexes.setdefault("inverted", {})[f.name] = InvertedIndex.build(
                            codes.astype(np.int64), card, total
                        )
                    if f.name in idx_cfg.range_index_columns:
                        indexes.setdefault("range", {})[f.name] = RangeEncodedIndex.build(
                            codes.astype(np.int64), card, total
                        )
            else:
                from pinot_tpu.segment.builder import narrow_ints

                card = int(len(np.unique(arr)))
                stats = collect_stats(f.name, f.data_type, arr, nmask, card, False)
                arr = narrow_ints(arr, nmask)
                vals = np.zeros(total, dtype=arr.dtype)
                vals[:n] = arr
                columns[f.name] = StackedColumn(
                    f.name, f.data_type, None, None, vals.reshape(num_shards, D), padded_nulls, stats
                )
        return StackedTable(schema, columns, valid.reshape(num_shards, D), n, indexes=indexes)

    @staticmethod
    def from_segments(
        segments: List[ImmutableSegment],
        num_shards: Optional[int] = None,
        table_config=None,
    ) -> "StackedTable":
        """Re-align N immutable segments onto a shared key space.

        Dictionary union + code remap per segment (the price Pinot pays per
        query in IndexedTable merges is paid once here at load time), then
        stack with padding.  num_shards defaults to len(segments); if given,
        segments are concatenated then re-split (e.g. 40 segments -> 8 shards
        on a v5e-8)."""
        if not segments:
            raise ValueError("no segments")
        schema = segments[0].schema
        names = schema.column_names
        # Upsert segments COMPACT at stack time: rows masked out of
        # validDocIds (replaced by newer rows elsewhere) are dropped here, so
        # the distributed engine needs no per-row valid mask at query time —
        # the load-time analog of the reference's UpsertCompaction minion task.
        keeps = [
            np.nonzero(seg.valid_docs)[0] if seg.valid_docs is not None else None
            for seg in segments
        ]
        # Re-decode per segment and concatenate; dictionary union via rebuild.
        data: Dict[str, np.ndarray] = {}
        null_cols: Dict[str, Optional[np.ndarray]] = {}
        for name in names:
            parts = []
            nparts = []
            any_nulls = False
            for seg, keep in zip(segments, keeps):
                c = seg.column(name)
                vals = np.asarray(c.decoded())
                nm = np.asarray(c.nulls) if c.nulls is not None else np.zeros(seg.num_docs, dtype=bool)
                if keep is not None:
                    vals = vals[keep]
                    nm = nm[keep]
                parts.append(vals)
                if c.nulls is not None:
                    any_nulls = True
                nparts.append(nm)
            data[name] = np.concatenate(parts)
            null_cols[name] = np.concatenate(nparts) if any_nulls else None
        S = num_shards or len(segments)
        # respect nullability via object arrays where needed — on a COPY of
        # the schema (mutating the caller's shared schema was round-2 weak #4)
        if any(null_cols[n] is not None and not schema.field(n).nullable for n in names):
            import dataclasses

            schema = Schema(
                name=schema.name,
                fields=[
                    dataclasses.replace(
                        f, nullable=f.nullable or null_cols[f.name] is not None
                    )
                    for f in schema.fields
                ],
                primary_key_columns=list(schema.primary_key_columns),
            )
        merged = {}
        for name in names:
            arr = data[name]
            if null_cols[name] is not None:
                arr = np.asarray(arr, dtype=object)
                arr[null_cols[name]] = None
            merged[name] = arr
        no_dict = tuple(
            f.name for f in schema.fields if not segments[0].column(f.name).has_dictionary
        )
        return StackedTable.build(
            schema, merged, S, no_dictionary_columns=no_dict, table_config=table_config
        )

    # -- device residency ----------------------------------------------
    def _use_packed(self, c: StackedColumn, sl, packed_codes: bool) -> bool:
        # packed shipping needs lane-aligned doc offsets (macro-batch
        # offsets are 32-aligned by _batching, so this always holds there)
        return bool(
            packed_codes
            and c.packed is not None
            and sl[0] % (32 // c.code_bits) == 0
            and sl[1] % (32 // c.code_bits) == 0
        )

    @staticmethod
    def _col_key(c: StackedColumn, sl, use_packed: bool):
        # cache by BACKING-ARRAY identity, not name: self-join facades
        # (aliased_view) rename columns but share the numpy storage —
        # identity keys mean one HBM copy serves every alias
        arr_id = id(c.codes if c.codes is not None else c.values)
        return (arr_id, sl, "#packed") if use_packed else (arr_id, sl)

    def device_group(self, mesh, sl) -> Tuple:
        """Residency cache-group key: ONE doc-slice of this table on one
        mesh.  Slices evict independently (a 4x-budget working set must be
        able to rotate through the cache), but all flavors of a slice drop
        as a unit."""
        return ("stacked", id(self), id(mesh), sl)

    def _plan_missing(self, mesh, cols, sl, packed_codes, with_valid):
        """(missing column specs, valid missing?, bytes to charge)."""
        span = sl[1] - sl[0]
        need = []
        nbytes = 0
        need_valid = False
        with self._device_lock:
            cache = self._device_cache.get(id(mesh), {})
            for cname in cols:
                c = self.columns[cname]
                use_packed = self._use_packed(c, sl, packed_codes)
                ck = self._col_key(c, sl, use_packed)
                if ck in cache:
                    continue
                dkey = cached_dict = None
                if c.codes is not None and c.dictionary is not None:
                    dvals = c.dictionary.device_values()
                    if dvals is not None:
                        dkey = (id(c.dictionary), "dict")
                        cached_dict = cache.get(dkey)
                        if cached_dict is None:
                            nbytes += dvals.nbytes
                        else:
                            dkey = None  # already staged (and charged) once
                if use_packed:
                    f = 32 // c.code_bits
                    nbytes += c.packed[:, sl[0] // f : sl[1] // f].nbytes
                elif c.codes is not None:
                    nbytes += c.codes[:, sl[0] : sl[1]].nbytes
                for arr in (c.values, c.nulls, c.mv_lengths):
                    if arr is not None:
                        nbytes += arr.itemsize * arr.shape[0] * span * (
                            int(np.prod(arr.shape[2:])) if arr.ndim > 2 else 1
                        )
                need.append((cname, ck, use_packed, dkey, cached_dict))
            if with_valid:
                vk = (id(self.valid), sl)
                if vk not in cache:
                    need_valid = True
                    nbytes += self.valid[:, sl[0] : sl[1]].nbytes
        return need, need_valid, nbytes

    def _stage_slice(self, need, need_valid, sl, row_sharding, rep_sharding):
        """Host->device copies for one slice's missing entries (NO locks
        held — this is the staging-stream body)."""
        import jax

        def _rows(a: np.ndarray) -> np.ndarray:
            if sl == (0, self.docs_per_shard):
                return a
            return np.ascontiguousarray(a[:, sl[0] : sl[1]])

        staged: Dict[Any, Any] = {}
        for cname, ck, use_packed, dkey, cached_dict in need:
            c = self.columns[cname]
            entry: Dict[str, Any] = {}
            if use_packed:
                f = 32 // c.code_bits
                w = c.packed[:, sl[0] // f : sl[1] // f]
                entry["codes_packed"] = jax.device_put(
                    np.ascontiguousarray(w), row_sharding
                )
            if c.codes is not None:
                if not use_packed:
                    entry["codes"] = jax.device_put(_rows(c.codes), row_sharding)
                if dkey is not None:
                    dvals = c.dictionary.device_values()
                    dput = jax.device_put(dvals, rep_sharding)
                    staged[dkey] = dput
                    entry["dict"] = dput
                elif cached_dict is not None:
                    entry["dict"] = cached_dict
            if c.values is not None:
                entry["values"] = jax.device_put(_rows(c.values), row_sharding)
            if c.nulls is not None:
                entry["nulls"] = jax.device_put(_rows(c.nulls), row_sharding)
            if c.mv_lengths is not None:
                entry["lengths"] = jax.device_put(_rows(c.mv_lengths), row_sharding)
            staged[ck] = entry
        if need_valid:
            staged[(id(self.valid), sl)] = jax.device_put(_rows(self.valid), row_sharding)
        return staged

    def _publish(self, mesh, group, staged) -> None:
        """First-wins publish + group-key registration in ONE critical
        section, so eviction can drop exactly this group's flavors."""
        with self._device_lock:
            cache = self._device_cache.setdefault(id(mesh), {})
            for k, v in staged.items():
                cache.setdefault(k, v)
            self._group_keys.setdefault(group, set()).update(staged.keys())

    def _assemble(self, mesh, cols, sl, packed_codes, with_valid):
        """Read the slice pytree in ONE critical section; None if a racing
        eviction removed any needed entry — callers re-stage the whole
        group, never observing a half-evicted slice."""
        with self._device_lock:
            cache = self._device_cache.get(id(mesh), {})
            out: Dict[str, Dict[str, Any]] = {}
            for cname in cols:
                c = self.columns[cname]
                ck = self._col_key(c, sl, self._use_packed(c, sl, packed_codes))
                if ck not in cache:
                    return None
                out[cname] = cache[ck]
            if not with_valid:
                # distributed-engine path: validity is computed IN-KERNEL
                # from static num_docs (padding is always trailing in the
                # global flat doc space by construction) — at 1B rows the
                # [S, D] bool buffer plus its while-loop capture copy is
                # ~2GB of HBM for a mask the kernel derives from an iota
                # compare.
                return out, None
            vk = (id(self.valid), sl)
            if vk not in cache:
                return None
            return out, cache[vk]

    def evict_slice(self, mesh, sl) -> None:
        """Atomic flavor invalidation for one slice group: every cache key
        the group charged — raw, #packed, valid, dictionaries it staged —
        drops in one critical section (residency eviction callback)."""
        group = self.device_group(mesh, sl)
        with self._device_lock:
            keys = self._group_keys.pop(group, set())
            cache = self._device_cache.get(id(mesh), {})
            for k in keys:
                cache.pop(k, None)

    def to_device(
        self,
        mesh=None,
        axis="seg",
        columns: Optional[List[str]] = None,
        doc_slice: Optional[Tuple[int, int]] = None,
        with_valid: bool = True,
        packed_codes: bool = False,
        residency=None,
        prefetch: bool = False,
        query_id: Optional[str] = None,
    ):
        """Shard row arrays over the mesh axis; dictionaries replicate.

        Returns (cols_pytree, valid) of jax arrays with NamedSharding — the
        input side of the shard_map combine kernel (parallel/engine.py).

        doc_slice=(lo, hi) ships only columns [:, lo:hi] of the [S, D] row
        arrays — the macro-batch launch path (parallel/engine.py batching):
        at 1B rows a single launch's while-loop capture copy alone exceeds
        HBM, so the engine slices the doc axis into batches and combines
        the table-sized partials across launches.

        With `residency` (segment/residency.py) the device cache is a
        byte-budgeted tier over the host arrays: each doc-slice is a cache
        group that charges the residency budget before copying (evicting
        cost-ranked victim slices to make room), at most one thread stages
        a group while the rest park on its event, and `prefetch=True` marks
        a stage issued ahead of need (the engine's double-buffered copy
        stream) for the prefetch-hit accounting."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        if mesh is None:
            from pinot_tpu.parallel.mesh import default_mesh

            mesh = default_mesh(axis)
        # `axis` may be one mesh axis name or the 2-D (replica, shard)
        # axes tuple: a tuple shards the leading [S, ...] dim jointly over
        # both axes (capacity mode — parallel/mesh.data_axes)
        row_sharding = NamedSharding(mesh, P(axis, None))
        rep_sharding = NamedSharding(mesh, P())
        cols = columns or list(self.columns)
        sl = doc_slice if doc_slice is not None else (0, self.docs_per_shard)
        group = self.device_group(mesh, sl)

        if residency is None:
            # legacy pin-everything path: no budget, no eviction
            while True:
                need, need_valid, _ = self._plan_missing(
                    mesh, cols, sl, packed_codes, with_valid
                )
                if need or need_valid:
                    staged = self._stage_slice(need, need_valid, sl, row_sharding, rep_sharding)
                    self._publish(mesh, group, staged)
                out = self._assemble(mesh, cols, sl, packed_codes, with_valid)
                if out is not None:
                    return out

        from pinot_tpu.segment import residency as res_mod
        from pinot_tpu.utils.crashpoints import crash_point

        while True:
            need, need_valid, _ = self._plan_missing(mesh, cols, sl, packed_codes, with_valid)
            st, entry = residency.begin_stage(
                group,
                self.schema.name,
                lambda: self.evict_slice(mesh, sl),
                prefetch=prefetch,
            )
            if st == res_mod.WAIT:
                residency.wait(entry)
                continue
            if st == res_mod.HIT:
                if not need and not need_valid:
                    out = self._assemble(mesh, cols, sl, packed_codes, with_valid)
                    if out is not None:
                        return out
                    continue  # evicted between plan and read: re-stage
                st2, entry2 = residency.begin_grow(group)
                if st2 == res_mod.WAIT:
                    residency.wait(entry2)
                    continue
                if st2 == res_mod.RETRY:
                    continue
            # OWN: charge, copy (no locks held), publish, commit
            try:
                need, need_valid, nbytes = self._plan_missing(
                    mesh, cols, sl, packed_codes, with_valid
                )
                residency.charge(group, nbytes, query_id=query_id)
                crash_point("segment.stage.after_charge")
                staged = self._stage_slice(need, need_valid, sl, row_sharding, rep_sharding)
                crash_point("segment.stage.after_copy")
                self._publish(mesh, group, staged)
            except BaseException:
                residency.abort_stage(group)
                raise
            residency.finish_stage(group)
            out = self._assemble(mesh, cols, sl, packed_codes, with_valid)
            if out is not None:
                return out

    def release_device(self) -> None:
        # in-place: self-join facades (aliased_view) share this dict by
        # reference — rebinding would leave their references pinning HBM
        with self._device_lock:
            self._device_cache.clear()
            self._group_keys.clear()

    # -- self-join facades ----------------------------------------------
    def aliased_view(self, alias: str) -> "StackedTable":
        """A facade of this table for SELF-JOINS: columns renamed to
        '{alias}${col}' so one query can reference two instances without
        name collisions (the reference resolves this in Calcite's scope
        binding; here it is a table-level rename).  Storage is SHARED — the
        facade's StackedColumn objects reference the same numpy arrays, and
        to_device's array-identity cache keys mean one HBM copy serves
        every alias."""
        import dataclasses as _dc

        from pinot_tpu.spi.schema import Schema as _Schema

        cols = {
            f"{alias}${n}": _dc.replace(c, name=f"{alias}${n}") for n, c in self.columns.items()
        }
        schema = _Schema(
            name=f"{self.schema.name}@{alias}",
            fields=[_dc.replace(f, name=f"{alias}${f.name}") for f in self.schema.fields],
            primary_key_columns=[f"{alias}${c}" for c in self.schema.primary_key_columns],
        )
        idx = {
            kind: {f"{alias}${n}": v for n, v in by_col.items()}
            for kind, by_col in self.indexes.items()
        }
        t = StackedTable(schema, cols, self.valid, self.num_docs, indexes=idx)
        t._device_lock = self._device_lock
        with self._device_lock:
            t._device_cache = self._device_cache
            t._group_keys = self._group_keys
        return t

    # -- host decode (selection gather) ---------------------------------
    def decoded_flat(self, name: str) -> np.ndarray:
        """Row-major decoded values (padding rows included; mask with valid)."""
        c = self.columns[name]
        if c.dictionary is not None:
            return c.dictionary.get_values(c.codes.reshape(-1))
        return c.values.reshape(-1)

    def decoded_rows(self, name: str, rows: np.ndarray) -> np.ndarray:
        """Decoded values for SPECIFIC flat doc ids — O(len(rows)) host work,
        never a full-column decode (selection gathers read a LIMIT-sized
        handful out of potentially 1B rows)."""
        c = self.columns[name]
        if c.dictionary is not None:
            return c.dictionary.get_values(c.codes.reshape(-1)[rows])
        return c.values.reshape(-1)[rows]
