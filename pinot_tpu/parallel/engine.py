"""Distributed query engine: one shard_map kernel per query over the mesh.

Reference parity: the whole distributed SSE path in one construct —
QueryRouter.submitQuery scatter (pinot-core/.../transport/QueryRouter.java:77)
+ BaseCombineOperator worker pool (.../combine/BaseCombineOperator.java:202)
+ BrokerReduceService merge (.../query/reduce/BrokerReduceService.java:65).

Re-design (SURVEY.md section 7 "Combine = collective"): there is no transport.
Segments live stacked+sharded in HBM across the mesh (stacked.py); a query
compiles to ONE shard_map kernel that filters/aggregates its local shard rows
and merges partials IN-GRAPH with lax.psum/pmin/pmax over the data axes.  The
host sees already-combined results; the remaining broker work (HAVING, ORDER
BY, LIMIT, formatting) reuses query/reduce.py verbatim.

The mesh may be the legacy 1-D SEG_AXIS mesh or the 2-D
(REPLICA_AXIS, SHARD_AXIS) mesh (parallel/mesh.py).  On 2-D, table rows
shard jointly over BOTH axes (capacity mode) and the combine is
HIERARCHICAL: reduce over SHARD_AXIS (ICI) first — collapsing each replica
row to one partial table — then once over REPLICA_AXIS, the only reduction
that crosses host/DCN boundaries on a multi-host pod, so cross-host bytes
scale with partial-table size rather than raw rows.  The QPS deployment of
the same mesh is ReplicatedEngine below: one 1-D sub-engine per replica
row, each a full data copy, whole same-fingerprint batches routed to rows
round-robin.

DataTable/Netty have no analog here by design: the wire format between
"servers" (shards) is an XLA collective over ICI/DCN (SURVEY.md 2.6).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pinot_tpu import ops
from pinot_tpu.parallel import mesh as mesh_mod
from pinot_tpu.query import executor as sse_executor
from pinot_tpu.query import reduce as reduce_mod
from pinot_tpu.query import planner as planner_mod
from pinot_tpu.query.filter import FilterCompiler
from pinot_tpu.query.functions import FIELD_COMBINE, get_agg_function
from pinot_tpu.query.ir import AggregationSpec, Expr, QueryContext
from pinot_tpu.query.planner import GroupDim, _group_dim
from pinot_tpu.query.result import (
    AggSegmentResult,
    DenseGroupData,
    ExecutionStats,
    GroupBySegmentResult,
    ResultTable,
    SelectionSegmentResult,
)
from pinot_tpu.query.transform import as_row_array, eval_expr
from pinot_tpu.utils import perf
from pinot_tpu.utils.metrics import METRICS


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: new jax exposes it top-level with
    `check_vma`; older releases (<= 0.4.x, this image) only have
    jax.experimental.shard_map with the `check_rep` spelling."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)


def _psum_field(name: str, x, axes):
    """Combine one partial field across the data axes, innermost axis
    (ICI) first — on the 2-D mesh the REPLICA_AXIS step is the only one
    that crosses host/DCN boundaries and it moves partial-table bytes.
    Float sums take the order-canonical path (mesh.psum_ordered): integer
    adds and min/max are exact under any association, but float partials
    must reduce in one fixed global order or 2x4 and 8x1 drift by ulps."""
    op = FIELD_COMBINE[name]
    if op == "add":
        if jnp.issubdtype(x.dtype, jnp.floating):
            return mesh_mod.psum_ordered(x, axes)
        return mesh_mod.psum_hierarchical(x, axes)
    if op == "min":
        return mesh_mod.pmin_hierarchical(x, axes)
    return mesh_mod.pmax_hierarchical(x, axes)


def flatten_cols(cols):
    """[S, D, ...] shard-local row arrays -> flat [S*D, ...] views.
    MV code matrices keep their trailing element axis."""
    out = {}
    for name, entry in cols.items():
        e = {}
        for k, v in entry.items():
            if k in ("codes", "codes_packed", "values", "nulls", "lengths"):
                e[k] = v.reshape((-1,) + v.shape[2:])
            else:
                e[k] = v
        out[name] = e
    return out


def make_agg_inputs(agg_specs, aggs, agg_filter_fns, view, table_like, null_handling):
    """Per-aggregation (values, mask) input builder usable inside kernels.

    Shared by the distributed SSE combine kernel and the MSE join kernels —
    the projection/transform step of the hot loop (ProjectionOperator /
    TransformOperator analog) specialised to one plan."""

    def _agg_inputs(cols, params, base_mask):
        out = []
        for spec, fn, ffn in zip(agg_specs, aggs, agg_filter_fns):
            mask = base_mask
            if ffn is not None:
                ft, _ = ffn(cols, params)
                mask = mask & ft
            if getattr(fn, "mv_input", False):
                out.append(planner_mod.mv_agg_input(spec, fn, view, cols, mask))
                continue
            if spec.expr is None:
                vals = mask
            elif fn.needs_codes:
                vals, mask = planner_mod.agg_input_codes(spec, fn, view, cols, mask, null_handling)
            elif fn.name == "count" and spec.expr.is_column:
                vals = mask
                c = table_like.column(spec.expr.op)
                if c.nulls is not None and null_handling:
                    mask = mask & ~cols[spec.expr.op]["nulls"]
            else:
                vals, nulls = eval_expr(spec.expr, view, cols)
                vals = as_row_array(vals, mask.shape)
                if nulls is not None and null_handling:
                    mask = mask & ~nulls
            if fn.needs_extra_exprs:
                extras = []
                for ex in spec.extra_exprs:
                    ev, en = eval_expr(ex, view, cols)
                    extras.append(as_row_array(ev, mask.shape))
                    if en is not None and null_handling:
                        mask = mask & ~en
                vals = (vals, *extras)
            out.append((vals, mask))
        return out

    return _agg_inputs


class _ShardView:
    """Compile-time segment facade over a StackedTable: FilterCompiler and
    transform tracing only consult metadata (dictionaries, nulls, dtypes) and
    num_docs for match-all shapes — here num_docs is the per-device flat row
    count for ONE launch (local shards x batch docs).

    When axis/ndev are given, FilterCompiler compiles SHARD-AWARE index
    paths: bitmap params stored full as [ndev, L, D//32] and sliced per
    macro-batch by the engine, doc ranges compare against global flat doc
    ids via `docs_fn` (query/filter.py)."""

    def __init__(
        self,
        stacked,
        local_rows: int,
        axis: Optional[str] = None,
        ndev: int = 0,
        docs_fn: Optional[Callable] = None,
        bitmap_layout: Optional[Tuple[int, int, int]] = None,
    ):
        self._stacked = stacked
        self.num_docs = local_rows
        self.schema = stacked.schema
        self.total_docs = stacked.num_docs
        self.indexes = getattr(stacked, "indexes", {})
        self.shard_info = (axis, ndev, local_rows) if axis is not None else None
        self.docs_fn = docs_fn
        self.bitmap_layout = bitmap_layout

    def column(self, name: str):
        return self._stacked.column(name)


@dataclass
class _DistPlan:
    kind: str  # aggregation | groupby_dense | groupby_sparse | selection
    fn: Callable  # jitted shard_map kernel(cols, params)
    params: Dict[str, Any]
    needed_columns: List[str]
    aggs: List[Any]
    group_dims: List[GroupDim]
    num_groups: int
    select_columns: List[str]
    # param keys sharded on the device axis (index bitmap word slices)
    row_sharded_params: frozenset = frozenset()
    # (column, index kind) per index-accelerated filter predicate
    index_uses: Tuple = ()
    # macro-batch launch schedule: each launch covers doc columns
    # [off, off+batch_docs) of the [S, D] arrays; `fresh` marks the first
    # not-yet-covered within-batch column (tail overlap masking)
    batch_docs: int = 0
    batch_offsets: Tuple[Tuple[int, int], ...] = ((0, 0),)
    # jitted device-side cross-launch merge for the sparse group-by path
    # (ops.merge_sparse_tables); None falls back to the host numpy merge
    sparse_merge_fn: Optional[Callable] = None
    # per-LAUNCH kernel cost model (utils/perf.KernelCost), captured at the
    # first dispatch and shared through the plan cache (hits copy it)
    cost: Optional[Any] = None


class DistributedEngine:
    """Executes queries over a StackedTable sharded on a device mesh."""

    def __init__(
        self,
        mesh=None,
        axis: str = mesh_mod.SEG_AXIS,
        launch_bytes: Optional[int] = None,
        pipeline_depth: Optional[int] = None,
        hbm_cache_bytes: Optional[int] = None,
        residency=None,
    ):
        import os

        if mesh is None:
            mesh = mesh_mod.default_mesh(axis)
        from pinot_tpu.query.planner import _plan_cache_entries
        from pinot_tpu.utils.cache import LruCache

        self.mesh = mesh
        # data-placement axes, outermost first: ("seg",) on the legacy 1-D
        # mesh, (REPLICA_AXIS, SHARD_AXIS) on the 2-D mesh.  `self.axis` is
        # what flows into PartitionSpecs and collectives — a bare name for
        # 1-D, the axes tuple for 2-D (both spellings every jax collective
        # accepts); hierarchical combines walk `self.axes` innermost-first.
        self.axes: Tuple[str, ...] = mesh_mod.data_axes(mesh)
        self.axis = self.axes[0] if len(self.axes) == 1 else self.axes
        self.tables: Dict[str, Any] = {}  # name -> StackedTable
        # plan-cache bytes charge the process host ledger the admission
        # controller tracks (runtime import: admission is cluster-layer)
        from pinot_tpu.cluster.admission import process_host_budget

        self._plan_cache = LruCache(
            max_entries=_plan_cache_entries(), name="compile.dist", budget=process_host_budget()
        )
        # vmapped-plan LRU for execute_many's cross-query batching: keyed on
        # the base compiled fn + lane width so batching never recompiles
        self._batch_fn_cache = LruCache(max_entries=32, name="compile.batch.dist")
        # shape fp + hit/miss of the most recent _plan call (trace/EXPLAIN
        # ANALYZE annotation; the engine plans one query at a time)
        self._last_shape_fp: str = ""
        self._last_plan_cache_hit = False
        # per-device bytes one launch may capture (macro-batching threshold);
        # ~2GB leaves the while-loop capture copy well under HBM headroom
        self.launch_bytes = (
            launch_bytes
            if launch_bytes is not None
            else int(os.environ.get("PINOT_TPU_LAUNCH_BYTES", str(2 << 30)))
        )
        # max in-flight macro-batch launches: 2 = double-buffering (dispatch
        # batch k+1 while batch k computes, hiding the host dispatch gap the
        # r5 timing_pairs spread exposed); 1 = the old fully-serialized loop.
        # Each in-flight launch holds a capture copy of its batch inputs, so
        # resident HBM scales with depth — _batching sizes batches against
        # launch_bytes, keeping depth * batch_bytes bounded.  None routes
        # through the autopilot KnobRegistry per launch (env var = initial
        # value + ceiling); an explicit ctor value or direct assignment pins.
        self._pipeline_depth_override: Optional[int] = (
            None if pipeline_depth is None else int(pipeline_depth)
        )
        # tiered segment storage (segment/residency.py): HBM is a byte-
        # budgeted cache over the host arrays.  The staging stream copies
        # batch k+1's slices while batch k computes — the generalization of
        # pipeline_depth from "launch next kernel" to "stage next segment".
        # PINOT_TPU_HBM_CACHE_BYTES sizes the cache (0 disables tiering and
        # restores the legacy pin-everything path).
        from pinot_tpu.segment.residency import default_residency

        if residency is not None:
            # caller-owned manager (ReplicatedEngine splits one HBM budget
            # into per-mesh-row managers so staging/eviction stays row-local)
            self.residency = residency
        elif hbm_cache_bytes is not None and hbm_cache_bytes > 0:
            from pinot_tpu.cluster.admission import ResourceBudget

            self.residency = default_residency(
                budget=ResourceBudget(hbm_cache_bytes, gauge="residency.reservedBytes")
            )
        elif hbm_cache_bytes is not None:
            self.residency = None
        else:
            self.residency = default_residency()

    @property
    def pipeline_depth(self) -> int:
        """In-flight launch depth, read per launch loop (KnobRegistry-backed
        unless pinned by the ctor or a direct assignment)."""
        if self._pipeline_depth_override is not None:
            return self._pipeline_depth_override
        # runtime import: autopilot is cluster-layer, engine is parallel-layer
        from pinot_tpu.cluster import autopilot

        return int(autopilot.knobs().get("pipeline_depth"))

    @pipeline_depth.setter
    def pipeline_depth(self, value: int) -> None:
        self._pipeline_depth_override = int(value)

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    def register_table(self, name: str, stacked) -> None:
        if stacked.num_shards % self.num_devices:
            raise ValueError(
                f"num_shards={stacked.num_shards} not divisible by mesh size {self.num_devices}"
            )
        self.tables[name] = stacked
        # HBM residency gauge: stacked host arrays mirror what to_device
        # pins across the mesh for this table
        nbytes = 0
        for c in stacked.columns.values():
            for arr in (c.codes, c.values, c.nulls, c.mv_lengths):
                if arr is not None:
                    nbytes += arr.nbytes
        METRICS.gauge(f"hbm.pinnedBytes.{name}").set(float(nbytes))
        # drop stale self-join facades of a re-registered table (mse/plan.py
        # resolve registers them as '{name}@{alias}')
        for k in [k for k in self.tables if k.startswith(name + "@")]:
            del self.tables[k]

    def _mse(self):
        """Join queries route to the multi-stage engine over the same mesh
        and table registry (MultiStageBrokerRequestHandler delegation analog)."""
        if not hasattr(self, "_mse_engine"):
            from pinot_tpu.mse.engine import MultiStageEngine

            self._mse_engine = MultiStageEngine(self.mesh, self.axis, tables=self.tables)
        return self._mse_engine

    # ------------------------------------------------------------------
    def query(self, sql: str) -> ResultTable:
        from pinot_tpu.sql.parser import parse_query

        return self.execute(parse_query(sql))

    def execute(self, ctx: QueryContext) -> ResultTable:
        import time

        if ctx.joins:
            return self._mse().execute(ctx)
        from pinot_tpu.utils.metrics import Trace

        t0 = time.perf_counter()
        trace = Trace(bool(ctx.options.get("trace", False)))
        stacked = self.tables[ctx.table]
        self._inject_sketch_info(ctx, stacked)
        stats = ExecutionStats(
            num_segments_queried=stacked.num_shards,
            num_segments_processed=stacked.num_shards,
            num_docs_scanned=stacked.num_docs,
            total_docs=stacked.num_docs,
        )
        with trace.span("plan") as psp:
            plan = self._plan(ctx, stacked)
            if psp is not None:
                from pinot_tpu.query.shape import shape_digest

                psp.annotate(
                    shapeFp=shape_digest(self._last_shape_fp),
                    planCache="hit" if self._last_plan_cache_hit else "miss",
                )
        stats.add_index_uses(plan.index_uses)
        with trace.span("run"):
            result = self._run(ctx, plan, stacked, stats, trace)
        with trace.span("reduce"):
            out = reduce_mod.reduce_results(ctx, [result], stats)
        t = trace.finish()
        if t is not None:
            out.stats.trace = t
        out.stats.time_ms = (time.perf_counter() - t0) * 1000
        METRICS.counter("dist.queries").inc()
        METRICS.histogram("dist.queryLatency").update(out.stats.time_ms)
        from pinot_tpu.query.shape import shape_digest

        perf.PERF_LEDGER.record(
            ctx.table,
            shape_digest(self._last_shape_fp),
            rows=out.stats.num_docs_scanned,
            time_ms=out.stats.time_ms,
            kernel_bytes=out.stats.kernel_bytes,
            compile_ms=out.stats.compile_ms,
            cache_hit=self._last_plan_cache_hit,
            engine="dist",
        )
        return out

    def execute_many(self, ctxs: List[QueryContext]) -> List[ResultTable]:
        """Cross-query batching at the distributed tier: queries sharing one
        compiled plan execute as a SINGLE vmapped launch with their literal
        params stacked on a leading query axis.

        Eligibility is deliberately narrow — aggregation / dense group-by
        plans with no row-sharded bitmap params and a single macro-batch
        (index bitmap doc-slicing and the pipelined multi-launch schedule
        don't compose with the query axis).  Ineligible queries, singleton
        groups, and any group whose vmap attempt fails fall back to
        sequential execute(), so results always match the unbatched path."""
        from pinot_tpu.query.shape import column_info_from, shape_digest

        results: List[Optional[ResultTable]] = [None] * len(ctxs)
        groups: Dict[Any, List[int]] = {}
        for i, ctx in enumerate(ctxs):
            if ctx.joins or ctx.set_ops or ctx.table not in self.tables:
                results[i] = self.execute(ctx)
                continue
            stacked = self.tables[ctx.table]
            key = (ctx.table, shape_digest(ctx.shape_fingerprint(column_info_from(stacked))))
            groups.setdefault(key, []).append(i)
        for idxs in groups.values():
            outs = self._execute_group([ctxs[i] for i in idxs]) if len(idxs) > 1 else None
            if outs is None:
                for i in idxs:
                    results[i] = self.execute(ctxs[i])
            else:
                for i, o in zip(idxs, outs):
                    results[i] = o
        return results

    def _execute_group(self, ctxs: List[QueryContext]) -> Optional[List[ResultTable]]:
        """One vmapped launch for a same-shape group; None = not eligible or
        the attempt failed (caller executes sequentially)."""
        import time as _time

        from pinot_tpu.query.shape import shape_digest

        table = ctxs[0].table
        stacked = self.tables[table]
        n = len(ctxs)
        t0 = _time.perf_counter()
        try:
            for ctx in ctxs:
                self._inject_sketch_info(ctx, stacked)
            plans = [self._plan(ctx, stacked) for ctx in ctxs]
            base = plans[0]
            if any(p.fn is not base.fn for p in plans[1:]):
                return None
            if base.kind not in ("aggregation", "groupby_dense"):
                return None
            if base.row_sharded_params or len(base.batch_offsets) != 1:
                return None
            width = sse_executor.batch_width()
            if n > width:
                return None
            cols, dev_params = self.device_batches(base, stacked)[0]
            pad_plans = plans + [plans[-1]] * (width - n)
            repl = NamedSharding(self.mesh, P())
            stacked_params = {}
            axes = {}
            for k in dev_params:
                if k in ("__boff__", "__fresh__"):
                    # launch-schedule scalars: identical across members
                    stacked_params[k] = dev_params[k]
                    axes[k] = None
                else:
                    stacked_params[k] = jax.device_put(
                        jax.tree_util.tree_map(
                            lambda *xs: np.stack([np.asarray(x) for x in xs]),
                            *(p.params[k] for p in pad_plans),
                        ),
                        repl,
                    )
                    axes[k] = 0
            key = (id(base.fn), width)
            fnb = self._batch_fn_cache.get(key)
            first_batched = fnb is None
            if first_batched:
                fnb = jax.jit(jax.vmap(base.fn, in_axes=(None, axes)))
                self._batch_fn_cache.put(key, fnb)
                sse_executor.BATCH_AUDIT.record_compile()
            else:
                sse_executor.BATCH_AUDIT.record_hit()
            if base.cost is None:
                base.cost = perf.capture_cost(
                    base.fn,
                    (cols, dev_params),
                    perf.analytic_cost(
                        stacked.num_shards * base.batch_docs,
                        perf.analytic_bytes_per_row(
                            stacked.column(nm) for nm in base.needed_columns
                        ),
                        kind=base.kind,
                        num_groups=base.num_groups,
                        num_entries=len(base.aggs),
                    ),
                )
            td0 = _time.perf_counter()
            host = jax.device_get(fnb(cols, stacked_params))
            compile_ms = (_time.perf_counter() - td0) * 1000.0 if first_batched else 0.0
        except Exception:
            METRICS.counter("dist.batchFallbacks").inc()
            return None
        share, rem = divmod(stacked.num_docs, n)
        outs = []
        for i, (ctx, plan) in enumerate(zip(ctxs, plans)):
            member = jax.tree_util.tree_map(lambda a: a[i], host)
            stats = ExecutionStats(
                num_segments_queried=stacked.num_shards,
                num_segments_processed=stacked.num_shards,
                num_docs_scanned=share + (1 if i < rem else 0),
                total_docs=stacked.num_docs,
            )
            stats.add_index_uses(plan.index_uses)
            if base.cost is not None:
                stats.kernel_bytes = base.cost.bytes_accessed / n
                stats.kernel_flops = base.cost.flops / n
                stats.kernel_cost_source = base.cost.source
            if i == 0 and compile_ms:
                stats.compile_ms = compile_ms
            if base.kind == "aggregation":
                result = AggSegmentResult(partials=member)
            else:
                presence, partials = member
                shim = SimpleNamespace(group_dims=base.group_dims, aggs=base.aggs)
                keys, sliced = sse_executor._dense_to_present(
                    shim, np.asarray(presence), partials, ctx.num_groups_limit,
                    order_trim=planner_mod.order_by_agg_index(ctx),
                )
                stats.num_groups = len(keys[0]) if keys else 0
                result = GroupBySegmentResult(
                    keys=keys,
                    partials=sliced,
                    dense=DenseGroupData(
                        presence=np.asarray(presence),
                        partials=partials,
                        key_space=tuple(
                            ("dict", gd.name, gd.dictionary.fingerprint(), gd.null_code)
                            if gd.kind == "dict"
                            else ("rawint", gd.name, gd.base, gd.cardinality)
                            for gd in base.group_dims
                        ),
                        group_dims=base.group_dims,
                    ),
                )
            out = reduce_mod.reduce_results(ctx, [result], stats)
            out.stats.time_ms = (_time.perf_counter() - t0) * 1000
            METRICS.counter("dist.queries").inc()
            METRICS.histogram("dist.queryLatency").update(out.stats.time_ms)
            perf.PERF_LEDGER.record(
                ctx.table,
                shape_digest(self._last_shape_fp),
                rows=out.stats.num_docs_scanned,
                time_ms=out.stats.time_ms,
                kernel_bytes=out.stats.kernel_bytes,
                compile_ms=out.stats.compile_ms,
                cache_hit=not first_batched,
                engine="dist",
            )
            outs.append(out)
        METRICS.counter("dist.batches").inc()
        METRICS.histogram("dist.batchSize").update(n)
        return outs

    @staticmethod
    def _inject_sketch_info(ctx: QueryContext, stacked) -> None:
        """Stacked tables are aligned by construction (one dictionary per
        column); publish that plus global ranges for sketch bindings."""
        from pinot_tpu.query.functions import for_spec

        for spec in ctx.aggregations:
            if spec.expr is None or not spec.expr.is_column:
                continue
            if not for_spec(spec).needs_binding:
                continue
            col = spec.expr.op
            c = stacked.column(col)
            ctx.options.setdefault(
                f"__dictfp__{col}", c.dictionary.fingerprint() if c.has_dictionary else ""
            )
            if c.has_dictionary:
                ctx.options.setdefault(f"__dictvals__{col}", c.dictionary.values)
            if c.stats.min_value is not None and not c.data_type.is_string_like:
                ctx.options.setdefault(f"__range__{col}", (c.stats.min_value, c.stats.max_value))

    # ------------------------------------------------------------------
    def _plan(self, ctx: QueryContext, stacked) -> _DistPlan:
        from pinot_tpu.analysis.compile_audit import DIST_AUDIT
        from pinot_tpu.analysis.plan_check import check_plan_cached
        from pinot_tpu.query.shape import column_info_from, params_structure

        check_plan_cached(ctx)
        batch_docs, batch_offsets = self._batching(ctx, stacked)
        # Keyed on the SHAPE fingerprint: predicate literals canonicalize to
        # parameter slots (query/shape.py), so 20 distinct-literal variants of
        # one query share this entry and only rebind params below.
        key = (
            ctx.shape_fingerprint(column_info_from(stacked)),
            stacked.signature(), self.axis, self.num_devices, batch_docs,
            ops.scan_backend(),  # pallas/xla plans trace different kernels
        )
        self._last_shape_fp = key[0]
        cached = self._plan_cache.get(key)
        if cached is not None:
            # Rebind this query's literals into a fresh plan that reuses the
            # cached compiled kernel (and device merge fn).  The structure
            # check guards against an audit miss: a jitted fn silently
            # retraces on a different params pytree, so a mismatch is a
            # compile and must be counted (and cached) as one.
            plan = self._build_plan(
                ctx, stacked, batch_docs, batch_offsets,
                compiled_fn=cached.fn, compiled_merge_fn=cached.sparse_merge_fn,
            )
            if (
                params_structure(plan.params) == params_structure(cached.params)
                and plan.row_sharded_params == cached.row_sharded_params
            ):
                # cost model rides the cache entry — captured at the cached
                # plan's first dispatch, never re-lowered on hits
                plan.cost = cached.cost
                DIST_AUDIT.record_hit(key[0])
                self._last_plan_cache_hit = True
                return plan
        DIST_AUDIT.record_compile(key[0])
        self._last_plan_cache_hit = False
        plan = self._build_plan(ctx, stacked, batch_docs, batch_offsets)
        self._plan_cache.put(key, plan)
        return plan

    def _batching(self, ctx: QueryContext, stacked) -> Tuple[int, Tuple[Tuple[int, int], ...]]:
        """Macro-batch launch schedule (round 5, VERDICT r4 #2).

        XLA materializes one copy of every while-loop-captured buffer, so a
        single launch's resident HBM is ~2x its input bytes — at 1B rows
        that alone exceeds a v5e chip.  Splitting the doc axis into B
        host-level launches caps the copy at one batch's bytes; the combine
        across launches is group-table-sized (never row-length).  Batch
        width is 32-aligned so index bitmap words slice cleanly; a ragged
        tail re-launches the last full-width window with already-covered
        rows masked via the `fresh` offset (same trick as
        ops/segmented._fused_scan_inchunk)."""
        D = stacked.docs_per_shard
        L = stacked.num_shards // self.num_devices
        # Per-doc bytes over the WHOLE table, not the query's needed columns:
        # batch width must be a pure function of the table so every query
        # shares one doc slicing — per-query widths would cache duplicate
        # on-device slices of the same column (review-caught: at 1B rows the
        # second slicing is the OOM the batching exists to prevent).  Narrow
        # queries over-batch slightly; launch overhead is microseconds.
        bytes_per_doc = 0.0
        for c in stacked.columns.values():
            if c.codes is not None:
                width = c.codes.shape[2] if c.codes.ndim == 3 else 1
                if getattr(c, "code_bits", None) and c.packed is not None:
                    # packed forward index ships the uint32 lane words
                    bytes_per_doc += c.code_bits / 8.0 * width
                else:
                    bytes_per_doc += c.codes.dtype.itemsize * width
            if c.values is not None:
                bytes_per_doc += c.values.dtype.itemsize
            if c.nulls is not None:
                bytes_per_doc += 1
            if c.mv_lengths is not None:
                bytes_per_doc += c.mv_lengths.dtype.itemsize
        per_dev = int(max(1.0, bytes_per_doc) * L * D)
        n_batches = max(1, -(-per_dev // self.launch_bytes))
        if n_batches == 1 or D < 64:
            return D, ((0, 0),)
        batch_docs = min(D, -(-(-(-D // n_batches)) // 32) * 32)
        offsets = []
        off = 0
        while off + batch_docs <= D:
            offsets.append((off, 0))
            off += batch_docs
        if off < D:
            tail = D - batch_docs
            offsets.append((tail, off - tail))
        return batch_docs, tuple(offsets)

    def _build_plan(
        self,
        ctx: QueryContext,
        stacked,
        batch_docs: int,
        batch_offsets: Tuple[Tuple[int, int], ...],
        compiled_fn: Optional[Callable] = None,
        compiled_merge_fn: Optional[Callable] = None,
    ) -> _DistPlan:
        axis = self.axis
        ndev = self.num_devices
        local_shards = stacked.num_shards // ndev
        D_full = stacked.docs_per_shard
        local_rows = local_shards * batch_docs
        L = local_shards
        Db = batch_docs
        has_padding = stacked.num_docs < stacked.num_shards * D_full
        use_fresh = any(fresh for _, fresh in batch_offsets)

        def docs_fn(params):
            """Global flat doc ids for this device's rows in this launch."""
            base = lax.axis_index(axis).astype(jnp.int32) * np.int32(L * D_full)
            off = params["__boff__"].astype(jnp.int32)
            return (
                base
                + off
                + jnp.arange(L, dtype=jnp.int32)[:, None] * np.int32(D_full)
                + jnp.arange(Db, dtype=jnp.int32)[None, :]
            ).reshape(-1)

        def _valid_mask(params):
            m = None
            if has_padding:
                m = docs_fn(params) < np.int32(stacked.num_docs)
            if use_fresh:
                f = jnp.tile(jnp.arange(Db, dtype=jnp.int32) >= params["__fresh__"], L)
                m = f if m is None else m & f
            return m

        assert D_full % 32 == 0, "docs_per_shard must be 32-aligned (StackedTable.build)"
        view = _ShardView(
            stacked, local_rows, axis=axis, ndev=ndev,
            docs_fn=docs_fn, bitmap_layout=(ndev, L, D_full // 32),
        )

        fc = FilterCompiler(view, ctx.null_handling)
        filter_fn = fc.compile(ctx.filter)
        # set when the WHOLE filter resolved to one plain index bitmap: the
        # fused Pallas scan can then consume the packed words directly and
        # the row-length bool mask never exists in HBM (capture before the
        # per-agg FILTER compiles below reuse the compiler)
        word_key = fc.sole_bitmap_param
        scan_be = ops.scan_backend()  # plan-time backend decision (cache-keyed)
        agg_specs = list(ctx.aggregations)
        aggs = planner_mod.bind_aggs(agg_specs, stacked, ctx)
        agg_filter_fns = [fc.compile(s.filter) if s.filter is not None else None for s in agg_specs]

        if ctx.is_aggregate and not ctx.group_by:
            kind = "aggregation"
            group_dims: List[GroupDim] = []
            num_groups = 0
        elif ctx.group_by:
            group_dims = [_group_dim(g, view, ctx.null_handling) for g in ctx.group_by]
            num_groups = 1
            for gd in group_dims:
                num_groups *= max(1, gd.cardinality)
            kind = "groupby_dense" if num_groups <= ctx.max_dense_groups else "groupby_sparse"
        else:
            kind = "selection"
            group_dims = []
            num_groups = 0

        planner_mod.guard_sparse_vector_fields(kind, aggs)
        if any(gd.mv for gd in group_dims):
            raise NotImplementedError("MV GROUP BY (explode) is not yet supported on the distributed stacked path")
        if kind in ("aggregation", "groupby_dense") and any(fn.pairwise_merge for fn in aggs):
            # the sparse path merges per-device tables HOST-side (pairwise
            # fn.merge in sparse_tables_to_result), so only the in-graph
            # psum-combined paths exclude coupled partials
            raise NotImplementedError(
                "pairwise-merge aggregations (FIRST/LAST_WITH_TIME, DISTINCTCOUNTTHETA) "
                "cannot ride the in-graph psum combine; run them on the single-node engine"
            )

        null_handling = ctx.null_handling
        # Bit-packed forward indexes: to_device(packed_codes=True) ships
        # uint32 lane words under "codes_packed" instead of the unpacked
        # codes; every kernel sees an overlay that adds trace-level unpacked
        # "codes" (XLA dedups/DCEs; the Pallas fused path additionally gets
        # the raw words via key_packed and unpacks in-register).
        packed_meta: Dict[str, int] = {
            name: int(c.code_bits)
            for name, c in stacked.columns.items()
            if getattr(c, "code_bits", None)
            and getattr(c, "packed", None) is not None
        }

        def _flat(cols, _rows=local_rows):
            from pinot_tpu.segment import packing

            out = flatten_cols(cols)
            for name, bits in packed_meta.items():
                e = out.get(name)
                if e is not None and "codes_packed" in e and "codes" not in e:
                    e = dict(e)
                    e["codes"] = packing.unpack_codes_jnp(
                        e["codes_packed"], bits, _rows
                    )
                    out[name] = e
            return out
        _agg_inputs = make_agg_inputs(agg_specs, aggs, agg_filter_fns, view, stacked, null_handling)

        def _group_key(cols):
            if len(group_dims) == 1 and group_dims[0].kind == "dict":
                return cols[group_dims[0].name]["codes"]  # cast per chunk in ops
            key = None
            for gd in group_dims:
                code = gd.device_code(cols, view, jnp.int32)
                key = code if key is None else key * np.int32(gd.cardinality) + code
            return key

        def _key_packed(cols):
            """(words, bits) for the group key when its bit-packed forward
            index shipped — lets the Pallas scan skip the unpacked codes."""
            if len(group_dims) != 1 or group_dims[0].kind != "dict":
                return None
            bits = packed_meta.get(group_dims[0].name)
            e = cols.get(group_dims[0].name)
            if not bits or e is None or "codes_packed" not in e:
                return None
            return (e["codes_packed"], bits)

        sparse_merge_fn = None  # set by the groupby_sparse branch when eligible

        if kind == "aggregation":

            def shard_kernel(cols, params):
                cols = _flat(cols)
                tmask, _ = filter_fn(cols, params)
                vm = _valid_mask(params)
                if vm is not None:
                    tmask = tmask & vm
                partials = [fn.partial(v, m) for fn, (v, m) in zip(aggs, _agg_inputs(cols, params, tmask))]
                return [
                    {f: _psum_field(f, x, axis) for f, x in p.items()} for p in partials
                ]

            out_specs = P()

        elif kind == "groupby_dense":
            vranges = planner_mod.agg_vranges(agg_specs, stacked)
            # Word fusion: when the whole filter is one plain index bitmap
            # and every aggregation is fully fusable (count/sum/sumsq field
            # kinds only — scatter and sketch paths never see packed words),
            # hand the PACKED words straight to the fused scan; the Pallas
            # kernel unpacks them in-register, so the filter costs 1 bit of
            # HBM per row instead of an unpacked bool byte.
            fuse_words = (
                scan_be in ("pallas", "interpret")
                and word_key is not None
                and all(fn.field_kinds is not None for fn in aggs)
                and all(
                    k in ("count", "sum", "sumsq")
                    for fn in aggs
                    for k in fn.field_kinds.values()
                )
            )

            if fuse_words:

                def shard_kernel(cols, params):
                    cols = _flat(cols)
                    vm = _valid_mask(params)
                    tmask = vm if vm is not None else jnp.ones((local_rows,), bool)
                    key = _group_key(cols)
                    inputs = _agg_inputs(cols, params, tmask)
                    presence, partials = planner_mod.grouped_partials(
                        aggs, inputs, tmask, key, num_groups, vranges,
                        backend=scan_be,
                        mask_words=params[word_key].reshape(-1),
                        key_packed=_key_packed(cols),
                    )
                    presence = mesh_mod.psum_hierarchical(presence, axis)
                    partials = [
                        {f: _psum_field(f, x, axis) for f, x in p.items()} for p in partials
                    ]
                    return presence, partials

            else:

                def shard_kernel(cols, params):
                    cols = _flat(cols)
                    tmask, _ = filter_fn(cols, params)
                    vm = _valid_mask(params)
                    if vm is not None:
                        tmask = tmask & vm
                    key = _group_key(cols)
                    inputs = _agg_inputs(cols, params, tmask)
                    presence, partials = planner_mod.grouped_partials(
                        aggs, inputs, tmask, key, num_groups, vranges,
                        backend=scan_be, key_packed=_key_packed(cols),
                    )
                    presence = mesh_mod.psum_hierarchical(presence, axis)
                    partials = [
                        {f: _psum_field(f, x, axis) for f, x in p.items()} for p in partials
                    ]
                    return presence, partials

            out_specs = P()

        elif kind == "groupby_sparse":
            # Per-device sort+scatter into fixed [numGroupsLimit] tables
            # (planner_mod.sparse_grouped_tables); only [ndev*K] tables cross
            # PCIe — never row-length arrays.  Cross-device key merge happens
            # host-side in sparse_tables_to_result (IndexedTable combine).
            if num_groups >= (1 << 62):
                raise NotImplementedError("composite group key exceeds 62 bits")
            num_slots = min(ctx.num_groups_limit, num_groups)
            # per-device ORDER BY-aware trim: each device keeps its LOCAL
            # top-num_slots groups by the comparator (groups split across
            # devices rank by local partials — the same accuracy valve as
            # the reference's server-side numGroupsLimit trim)
            order_spec = planner_mod.kernel_order_spec(ctx, aggs)

            def shard_kernel(cols, params):
                cols = _flat(cols)
                tmask, _ = filter_fn(cols, params)
                vm = _valid_mask(params)
                if vm is not None:
                    tmask = tmask & vm
                key = planner_mod.packed_key64(cols, group_dims, view)
                inputs = _agg_inputs(cols, params, tmask)
                return planner_mod.sparse_grouped_tables(
                    aggs, inputs, tmask, key, num_slots, order_spec
                )

            out_specs = P(self.axis)

            # Device-side cross-launch merge (ops.merge_sparse_tables): the
            # stacked [B*ndev*K] per-launch tables combine in-graph and only
            # the FINAL [num_slots] tables cross PCIe — replacing the host
            # numpy fold of sparse_tables_to_result.  Eligible when every
            # aggregation merges field-wise (field_kinds set, no pairwise
            # merge) and any ORDER BY-aware trim is expressible on device
            # (kernel_order_spec); otherwise the host merge remains.
            sparse_merge_fn = None
            merge_ok = all(
                fn.field_kinds is not None and not fn.pairwise_merge for fn in aggs
            )
            morder = None
            if merge_ok and planner_mod.order_by_agg_index(ctx) is not None:
                if order_spec is None:
                    merge_ok = False  # host ranks via fn.final; not derivable here
                else:
                    morder = order_spec  # (agg index, order FIELD name, asc)
            if merge_ok:
                field_ops = [
                    {f: FIELD_COMBINE[f] for f in fn.fields} for fn in aggs
                ]

                def _merge(uniq_list, parts_list):
                    uniq = jnp.concatenate([u.reshape(-1) for u in uniq_list])
                    parts = [
                        {
                            f: jnp.concatenate([p[i][f].reshape(-1) for p in parts_list])
                            for f in field_ops[i]
                        }
                        for i in range(len(field_ops))
                    ]
                    return ops.merge_sparse_tables(
                        uniq, parts, num_slots, field_ops, order_spec=morder
                    )

                sparse_merge_fn = (
                    compiled_merge_fn if compiled_merge_fn is not None else jax.jit(_merge)
                )

        else:  # selection

            def shard_kernel(cols, params):
                cols = _flat(cols)
                tmask, _ = filter_fn(cols, params)
                vm = _valid_mask(params)
                if vm is not None:
                    tmask = tmask & vm
                return tmask

            out_specs = P(self.axis)

        # in_specs matching the pytrees: row arrays shard on the leading axis,
        # dictionaries and params replicate.
        def _col_specs(cols):
            out = {}
            for name, entry in cols.items():
                out[name] = {
                    k: (
                        P(axis, *([None] * (v.ndim - 1)))
                        if k in ("codes", "codes_packed", "values", "nulls", "lengths")
                        else P()
                    )
                    for k, v in entry.items()
                }
            return out

        select_columns: List[str] = []
        if kind == "selection":
            for s in ctx.select_list:
                if isinstance(s, Expr) and s.is_column:
                    if s.op == "*":
                        select_columns.extend(stacked.schema.column_names)
                    else:
                        select_columns.append(s.op)
                else:
                    raise NotImplementedError(f"selection expression {s} not yet supported")

        mesh = self.mesh
        # launch-schedule params: batch doc offset + fresh floor (tail
        # overlap masking); always present so every batch shares one pytree
        fc.params["__boff__"] = np.int32(0)
        fc.params["__fresh__"] = np.int32(0)
        row_sharded = frozenset(fc.row_sharded_params)

        def run(cols, params):
            kern = shard_map_compat(
                shard_kernel,
                mesh=mesh,
                in_specs=(
                    _col_specs(cols),
                    {k: (P(axis, None) if k in row_sharded else P()) for k in params},
                ),
                out_specs=out_specs,
            )
            return kern(cols, params)

        # On a shape-cache hit the caller passes the already-jitted kernel:
        # this rebuild only re-derives params/metadata, never re-traces.
        fn = compiled_fn if compiled_fn is not None else jax.jit(run)

        needed = sse_executor_needed_columns(ctx, stacked)
        # index-resolved filter columns never ship to device (the bitmap/doc
        # range already answered them) — same pruning as the SSE planner
        keep = planner_mod._non_filter_columns(ctx, view) | fc.used_columns
        if kind == "selection":
            keep |= set(select_columns) | {o.expr.op for o in ctx.order_by if o.expr.is_column}
        needed = [c for c in needed if c in keep]
        return _DistPlan(
            kind=kind,
            fn=fn,
            params=fc.params,
            needed_columns=needed,
            aggs=aggs,
            group_dims=group_dims,
            num_groups=num_groups,
            select_columns=select_columns,
            row_sharded_params=frozenset(fc.row_sharded_params),
            index_uses=tuple(fc.index_uses),
            batch_docs=batch_docs,
            batch_offsets=tuple(batch_offsets),
            sparse_merge_fn=sparse_merge_fn,
        )

    # ------------------------------------------------------------------
    def batch_params(self, plan: _DistPlan, off: int, fresh: int) -> Dict[str, Any]:
        """Host-side params for the launch covering docs [off, off+batch_docs):
        schedule scalars set, row-sharded bitmap words sliced on the doc axis."""
        p = dict(plan.params)
        p["__boff__"] = np.int32(off)
        p["__fresh__"] = np.int32(fresh)
        wlo, whi = off // 32, (off + plan.batch_docs) // 32
        for k in plan.row_sharded_params:
            w = plan.params[k]  # [ndev, L, D//32]
            p[k] = np.ascontiguousarray(w[:, :, wlo:whi]).reshape(w.shape[0], -1)
        return p

    def _shared_params(self, plan: _DistPlan):
        """Batch-invariant params stage ONCE per query: only the launch-
        schedule scalars (__boff__/__fresh__) and the doc-sliced row-sharded
        bitmap words differ between launches, so the shared device_put cost
        does not scale with the launch count."""
        repl = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P(self.axis, None))
        shared = {
            k: jax.device_put(v, repl)
            for k, v in plan.params.items()
            if k not in plan.row_sharded_params and k not in ("__boff__", "__fresh__")
        }
        return shared, repl, shard

    def _stage_batch(
        self, plan: _DistPlan, stacked, j: int, shared, repl, shard, prefetch: bool = False
    ) -> Tuple[Dict, Dict]:
        """Stage macro-batch j's device inputs: the table slice rides the
        residency cache (budgeted, evictable), per-batch params ship fresh.
        Runs on the residency staging stream when called with prefetch."""
        off, fresh = plan.batch_offsets[j]
        cols, _ = stacked.to_device(
            self.mesh, self.axis, plan.needed_columns,
            doc_slice=(off, off + plan.batch_docs), with_valid=False,
            packed_codes=True, residency=self.residency, prefetch=prefetch,
        )
        params = dict(shared)
        for k, v in self.batch_params(plan, off, fresh).items():
            if k in shared:
                continue
            params[k] = jax.device_put(v, shard if k in plan.row_sharded_params else repl)
        return cols, params

    def device_batches(self, plan: _DistPlan, stacked) -> List[Tuple[Dict, Dict]]:
        """Device-placed (cols, params) per macro-batch launch (bench.py's
        marginal-timing hook shares this with _run; _run itself stages
        lazily through the prefetch stream instead of materializing the
        whole list)."""
        shared, repl, shard = self._shared_params(plan)
        return [
            self._stage_batch(plan, stacked, j, shared, repl, shard)
            for j in range(len(plan.batch_offsets))
        ]

    @staticmethod
    def _combine_partials(parts_list):
        """Fold per-batch partials (list over batches of list-of-field-dicts)
        with the same add/min/max semantics as the in-graph psum combine
        (functions.combine_field — the one FIELD_COMBINE dispatch)."""
        from pinot_tpu.query.functions import combine_field

        out = parts_list[0]
        for nxt in parts_list[1:]:
            out = [
                {f: combine_field(f, p[f], q[f]) for f in p}
                for p, q in zip(out, nxt)
            ]
        return out

    def _drain(self, out, keep_device: bool):
        """Completion fence for one in-flight launch.  keep_device leaves the
        output tables on device (the sparse merge consumes them in-graph) and
        fences on a single table-sized leaf instead of copying everything —
        one small device_get, not a per-launch block_until_ready."""
        if keep_device:
            jax.device_get(jax.tree_util.tree_leaves(out)[0])
            return out
        return jax.device_get(out)

    def _run(self, ctx, plan: _DistPlan, stacked, stats: ExecutionStats, trace=None):
        from pinot_tpu.utils.metrics import Trace

        if trace is None:
            trace = Trace(False)
        # Launches are PIPELINED up to pipeline_depth in flight (default 2 =
        # double-buffering): batch k+1 dispatches while batch k computes,
        # hiding the host-side dispatch/relay gap between launches.  Each
        # in-flight execution holds a capture copy of its batch inputs, so
        # resident HBM is bounded by depth * batch bytes (depth=1 restores
        # the old fully-serialized loop).  The fence is a device_get of the
        # oldest launch's output — never a per-launch block_until_ready.
        depth = max(1, int(self.pipeline_depth))
        # graceful degradation: under process-wide memory pressure (broker
        # admission controller, cluster/admission.py) the pipeline sheds
        # in-flight launches — one fewer capture copy resident in HBM per
        # pressure level past 1, down to a fully serialized loop
        from pinot_tpu.cluster.admission import current_pressure_level, pipeline_depth_under_pressure

        pressure = current_pressure_level()
        if pressure:
            depth = pipeline_depth_under_pressure(depth, pressure)
            trace.annotate(pressure=pressure)
        # device merge consumes sparse outputs in-graph: keep them on device
        keep_device = plan.kind == "groupby_sparse" and plan.sparse_merge_fn is not None
        batch_outs = []
        pending: List[Any] = []
        launch_rows = stacked.num_shards * plan.batch_docs  # rows per launch
        n_batches = len(plan.batch_offsets)
        # Staging pipeline: with a residency manager attached, batch j+1's
        # host->device copies run on the residency staging thread while
        # batch j computes — the "stage next segment" generalization of the
        # launch pipeline below.  Without one (tiering disabled) staging is
        # inline, restoring the legacy pin-everything behaviour.  The single
        # staging worker keeps copies FIFO, so consuming j never waits
        # behind a copy issued for j+1.
        shared, repl, shard = self._shared_params(plan)
        use_stream = self.residency is not None and n_batches > 1
        staged: Dict[int, Any] = {}

        def _ensure(j: int, prefetch: bool) -> None:
            if j >= n_batches or j in staged:
                return
            if use_stream:
                staged[j] = self.residency.submit(
                    self._stage_batch, plan, stacked, j, shared, repl, shard, prefetch
                )
            else:
                staged[j] = self._stage_batch(plan, stacked, j, shared, repl, shard)

        def _consume(j: int) -> Tuple[Dict, Dict]:
            item = staged.pop(j)
            if not use_stream:
                return item
            if item.done():
                METRICS.counter("engine.prefetchHits").inc()
                return item.result()
            # the copy stream is behind the compute stream: timed stall
            tw0 = time.perf_counter()
            out = item.result()
            METRICS.counter("engine.stagingStalls").inc()
            METRICS.histogram("residency.stagingStallMs").update(
                (time.perf_counter() - tw0) * 1000.0
            )
            return out

        tl0 = time.perf_counter()
        with trace.span("launches") as lsp:
            _ensure(0, False)
            for i in range(n_batches):
                for j in range(i + 1, min(i + 1 + depth, n_batches)):
                    _ensure(j, True)
                with trace.span(f"stage:{i}"):
                    cols, params = _consume(i)
                first_dispatch = i == 0 and plan.cost is None
                if first_dispatch:
                    # cost model captured ONCE per cached plan (per LAUNCH —
                    # every batch shares the shape, so one model covers all)
                    plan.cost = perf.capture_cost(
                        plan.fn,
                        (cols, params),
                        perf.analytic_cost(
                            launch_rows,
                            perf.analytic_bytes_per_row(
                                (stacked.column(n) for n in plan.needed_columns),
                                bitmap_params=len(plan.row_sharded_params),
                            ),
                            kind=plan.kind,
                            num_groups=plan.num_groups,
                            num_entries=len(plan.aggs),
                        ),
                    )
                td0 = time.perf_counter()
                with trace.span(f"dispatch:{i}"):
                    pending.append(plan.fn(cols, params))
                if first_dispatch:
                    # the first jit dispatch pays trace+compile; its wall
                    # time is the compile cost this query actually paid
                    plan.cost.compile_ms = (time.perf_counter() - td0) * 1000.0
                    stats.compile_ms += plan.cost.compile_ms + plan.cost.lower_ms
                if len(pending) >= depth:
                    with trace.span("drain"):
                        batch_outs.append(self._drain(pending.pop(0), keep_device))
            while pending:
                with trace.span("drain"):
                    batch_outs.append(self._drain(pending.pop(0), keep_device))
            # every drain is a device_get fence, so the launches-section wall
            # time bounds device compute — the roofline denominator here
            launch_s = time.perf_counter() - tl0
            total_bytes = total_flops = 0.0
            if plan.cost is not None:
                n_launches = len(plan.batch_offsets)
                total_bytes = plan.cost.bytes_accessed * n_launches
                total_flops = plan.cost.flops * n_launches
                stats.kernel_bytes += total_bytes
                stats.kernel_flops += total_flops
                stats.kernel_cost_source = plan.cost.source
                stats.device_ms += launch_s * 1000.0
            if lsp is not None:
                roof = perf.roofline_pct(total_bytes, launch_s)
                lsp.annotate(
                    batches=len(plan.batch_offsets),
                    pipelineDepth=depth,
                    backend=ops.scan_backend(),
                    kernelBytes=total_bytes,
                    kernelFlops=total_flops,
                    costSource=plan.cost.source if plan.cost is not None else None,
                    **({"rooflinePct": round(roof, 2)} if roof is not None else {}),
                )

        if plan.kind == "aggregation":
            partials = self._combine_partials(batch_outs)
            return AggSegmentResult(partials=partials)

        if plan.kind == "groupby_dense":
            presence = np.asarray(batch_outs[0][0])
            for p, _ in batch_outs[1:]:
                presence = presence + np.asarray(p)
            partials = self._combine_partials([p for _, p in batch_outs])
            dense = DenseGroupData(
                presence=presence,
                partials=partials,
                key_space=tuple(
                    ("dict", gd.name, gd.dictionary.fingerprint(), gd.null_code)
                    if gd.kind == "dict"
                    else ("rawint", gd.name, gd.base, gd.cardinality)
                    for gd in plan.group_dims
                ),
                group_dims=plan.group_dims,
            )
            shim = SimpleNamespace(group_dims=plan.group_dims, aggs=plan.aggs)
            keys, sliced = sse_executor._dense_to_present(
                shim, presence, partials, ctx.num_groups_limit,
                order_trim=planner_mod.order_by_agg_index(ctx),
            )
            stats.num_groups = len(keys[0]) if keys else 0
            return GroupBySegmentResult(keys=keys, partials=sliced, dense=dense)

        if plan.kind == "groupby_sparse":
            if plan.sparse_merge_fn is not None:
                # device merge: the [B*ndev*K] stacked tables combine
                # in-graph (ops.merge_sparse_tables, order-aware trim
                # included) and only the final [num_slots] tables come home
                with trace.span("sparse_merge:device"):
                    merged = plan.sparse_merge_fn(
                        [u for u, _ in batch_outs], [p for _, p in batch_outs]
                    )
                    uniq, partials = jax.device_get(merged)
                res = sse_executor.sparse_tables_to_result(
                    plan.group_dims, plan.aggs, np.asarray(uniq), partials,
                    ctx.num_groups_limit, order_trim=None, assume_unique=True,
                )
                stats.num_groups = len(res.keys[0]) if res.keys else 0
                return res
            # host fallback (pairwise-merge partials or an ORDER BY rank the
            # device cannot derive): batches concatenate like extra devices
            # and sparse_tables_to_result folds duplicate keys on host
            with trace.span("sparse_merge:host"):
                uniq = np.concatenate([np.asarray(u).reshape(-1) for u, _ in batch_outs])
                partials = [
                    {
                        f: np.concatenate([np.asarray(p[i][f]) for _, p in batch_outs])
                        for f in batch_outs[0][1][i]
                    }
                    for i in range(len(batch_outs[0][1]))
                ]
                res = sse_executor.sparse_tables_to_result(
                    plan.group_dims, plan.aggs, uniq, partials, ctx.num_groups_limit,
                    order_trim=planner_mod.order_by_agg_index(ctx),
                )
            stats.num_groups = len(res.keys[0]) if res.keys else 0
            return res

        # selection: reassemble the [S, D] mask from the per-batch doc slices
        # (only the fresh part of a ragged tail writes back)
        S, D = stacked.num_shards, stacked.docs_per_shard
        if plan.batch_offsets == ((0, 0),) and plan.batch_docs == D:
            tmask = np.asarray(batch_outs[0])
        else:
            tmask = np.zeros((S, D), dtype=bool)
            for (off, fresh), out in zip(plan.batch_offsets, batch_outs):
                m = np.asarray(out).reshape(S, plan.batch_docs)
                tmask[:, off + fresh : off + plan.batch_docs] = m[:, fresh:]
        return self._gather_selection(ctx, plan, stacked, tmask)

    def _gather_selection(self, ctx, plan: _DistPlan, stacked, tmask: np.ndarray) -> SelectionSegmentResult:
        docids = np.nonzero(tmask.reshape(-1))[0]
        want = ctx.offset + ctx.limit
        if ctx.order_by:
            for ob in ctx.order_by:
                if not ob.expr.is_column:
                    raise NotImplementedError("selection ORDER BY supports bare columns only")
            if len(docids) > want:
                # Codes are GLOBAL sort ranks here (one shared dictionary), so
                # a numeric lexsort on codes is a correct global top-k.
                lex_keys: List[np.ndarray] = []
                for ob in reversed(ctx.order_by):
                    c = stacked.column(ob.expr.op)
                    key, null_rank = sse_executor.order_key_arrays(
                        c.codes.reshape(-1) if c.codes is not None else None,
                        c.values.reshape(-1) if c.values is not None else None,
                        c.nulls.reshape(-1) if c.nulls is not None else None,
                        docids, ob.ascending, ob.nulls_last,
                    )
                    lex_keys.append(key)
                    if null_rank is not None:
                        lex_keys.append(null_rank)
                order = np.lexsort(tuple(lex_keys))[:want]
                docids = docids[order]
        else:
            docids = docids[:want]

        arrays: Dict[str, np.ndarray] = {}

        def _decoded(name: str) -> np.ndarray:
            c = stacked.column(name)
            vals = stacked.decoded_rows(name, docids)
            if c.nulls is not None and ctx.null_handling:
                vals = np.asarray(vals, dtype=object)
                vals[c.nulls.reshape(-1)[docids]] = None
            return vals

        for name in plan.select_columns:
            arrays[name] = _decoded(name)
        for i, ob in enumerate(ctx.order_by):
            arrays[f"__ord{i}"] = _decoded(ob.expr.op)
        cols_out = plan.select_columns + [f"__ord{i}" for i in range(len(ctx.order_by))]
        return SelectionSegmentResult(columns=cols_out, arrays=arrays)


def sse_executor_needed_columns(ctx: QueryContext, stacked) -> List[str]:
    """Column set the kernel touches (planner._needed_columns against the
    stacked facade)."""
    from pinot_tpu.query.planner import _needed_columns

    view = SimpleNamespace(schema=stacked.schema)
    return _needed_columns(ctx, view)


class ReplicatedEngine:
    """QPS deployment of the 2-D mesh: one 1-D DistributedEngine per
    replica row, each holding a FULL copy of every registered table on its
    own disjoint device set (replica-group serving, SURVEY.md 2.5).

    Routing follows the r14 micro-batcher contract: whole same-fingerprint
    batches go to one replica row, rows rotate round-robin — concurrent
    load spreads across rows so sustained QPS scales with R while each
    row's plan/device caches stay hot (a per-query spray would cold-start
    every row's cache on every shape).

    Placement is CoordinatorHandle-driven when a coordinator is attached:
    `mesh_placement(R)` maps journaled replica groups onto mesh rows, so
    rebalance and leader failover move the routing view and the mesh
    placement together — a row whose replica group has no live server is
    skipped by the round-robin until it recovers.

    Each row gets its OWN residency manager with an even share of the HBM
    cache budget (segment/residency.row_residency): staging and eviction
    are row-local, so one row's working set never evicts another's."""

    def __init__(
        self,
        mesh=None,
        num_replicas: int = 2,
        hbm_cache_bytes: Optional[int] = None,
        coordinator=None,
        **engine_kwargs,
    ):
        import threading

        if mesh is None:
            mesh = mesh_mod.make_mesh2d(num_replicas)
        self.mesh = mesh
        rows = mesh_mod.replica_rows(mesh)
        from pinot_tpu.segment.residency import row_residency

        self.engines: List[DistributedEngine] = []
        for r, row_mesh in enumerate(rows):
            res = row_residency(len(rows), r, total_bytes=hbm_cache_bytes)
            self.engines.append(
                DistributedEngine(
                    row_mesh,
                    axis=row_mesh.axis_names[0],
                    residency=res,
                    hbm_cache_bytes=0 if res is None else None,
                    **engine_kwargs,
                )
            )
        self.coordinator = coordinator
        self._rr = 0
        self._rr_lock = threading.Lock()
        # One dispatch lock per row: a row's collectives must never
        # interleave with another in-flight program on the SAME device set
        # (XLA's CPU collective rendezvous deadlocks when two programs'
        # participants mix).  Concurrency comes from having R rows — each
        # row is one serving pipeline — not from racing a row's mesh.
        self._row_locks = [threading.Lock() for _ in self.engines]

    @property
    def num_replicas(self) -> int:
        return len(self.engines)

    def register_table(self, name: str, stacked) -> None:
        for eng in self.engines:
            eng.register_table(name, stacked)

    def _live_rows(self) -> List[int]:
        """Rows eligible for routing: all of them standalone; with a
        coordinator attached, only rows whose mapped replica group still
        has a live server (failover parks a dead row out of the rotation
        exactly as the broker's routing view drops its servers)."""
        all_rows = list(range(len(self.engines)))
        if self.coordinator is None:
            return all_rows
        placement = self.coordinator.mesh_placement(len(self.engines))
        live = [r for r in all_rows if placement.get(r)]
        return live or all_rows

    def _next_row(self) -> int:
        rows = self._live_rows()
        with self._rr_lock:
            self._rr += 1
            return rows[self._rr % len(rows)]

    def query(self, sql: str) -> ResultTable:
        row = self._next_row()
        with self._row_locks[row]:
            return self.engines[row].query(sql)

    def execute(self, ctx: QueryContext) -> ResultTable:
        row = self._next_row()
        with self._row_locks[row]:
            return self.engines[row].execute(ctx)

    def execute_many(self, ctxs: List[QueryContext]) -> List[ResultTable]:
        """Batch routing: members group by shape fingerprint and every
        group lands WHOLE on one replica row (vmapped same-shape launches
        never split across rows), rows rotating per group."""
        from pinot_tpu.query.shape import column_info_from, shape_digest

        results: List[Optional[ResultTable]] = [None] * len(ctxs)
        groups: Dict[Any, List[int]] = {}
        for i, ctx in enumerate(ctxs):
            stacked = self.engines[0].tables.get(ctx.table)
            if ctx.joins or ctx.set_ops or stacked is None:
                results[i] = self.execute(ctx)
                continue
            key = (ctx.table, shape_digest(ctx.shape_fingerprint(column_info_from(stacked))))
            groups.setdefault(key, []).append(i)
        for idxs in groups.values():
            row = self._next_row()
            with self._row_locks[row]:
                outs = self.engines[row].execute_many([ctxs[i] for i in idxs])
            for i, out in zip(idxs, outs):
                results[i] = out
        return results
