"""Device mesh helpers: the single source of truth for mesh axis names.

Reference parity: the scatter axis of Pinot's deployment — segments spread
over servers, replicas over replica-groups (SURVEY.md 2.5).  TPU-native form:
a 2-D ``jax.make_mesh((R, S), (REPLICA_AXIS, SHARD_AXIS))`` whose axes name
the two parallelism strategies:

  shard    - horizontal data partitioning (scatter-gather analog): shards of
             the stacked table live on distinct devices and partial results
             combine in-graph over ICI.
  replica  - replica rows for QPS scaling: each mesh row holds a full copy
             of the data on its own 1-D shard submesh; the router
             (cluster/broker round-robin over rows) picks one per batch.

The legacy single-host form is a 1-D ``SEG_AXIS`` mesh — equivalent to
(R=1) with the shard axis named "seg".  Both spellings flow through the
engines as an *axes tuple* (``data_axes``), ordered outermost-first:
``(REPLICA_AXIS, SHARD_AXIS)``.  Cross-device combines must walk that tuple
innermost-first (``combine_hierarchical``): the shard reduction rides ICI
and shrinks the operand to one partial table per replica row, so the single
outer reduction — the only one that crosses host/DCN boundaries on a
multi-host pod — moves partial-table bytes, not raw rows.

Axis names are exported as constants; kernels must not spell them as bare
string literals at collective call sites (repo_lint W025) so a topology
rename cannot silently desynchronize a kernel from the mesh it runs on.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

#: QPS axis: replica rows, each a full data copy (cross-host / DCN on pods).
REPLICA_AXIS = "replica"
#: Capacity axis: table shards within one replica row (intra-host / ICI).
SHARD_AXIS = "shard"
#: Legacy 1-D data axis used by the single-host engines since M2.
SEG_AXIS = "seg"

#: Canonical 2-D data-placement axes, outermost (DCN) first.
DATA_AXES: Tuple[str, str] = (REPLICA_AXIS, SHARD_AXIS)

AxisSpec = Union[str, Sequence[str]]


def normalize_axes(axis: AxisSpec) -> Tuple[str, ...]:
    """Coerce a single axis name or a sequence of them to a tuple."""
    if isinstance(axis, str):
        return (axis,)
    return tuple(axis)


def default_mesh(axis: str = SEG_AXIS, num_devices: Optional[int] = None):
    """1-D mesh over all (or the first N) local devices."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_mesh2d(
    num_replicas: int = 1,
    num_shards: Optional[int] = None,
    num_devices: Optional[int] = None,
):
    """2-D (REPLICA_AXIS, SHARD_AXIS) mesh: R replica rows of S shards each.

    ``num_shards`` defaults to devices/num_replicas.  Raises with a clear
    message when the device count does not factor (e.g. 8 devices into 3
    replica rows).  Prefers ``jax.make_mesh`` so the device order respects
    the physical interconnect (ICI-contiguous shard rows on real pods).
    """
    import jax

    devs = jax.devices()
    n = len(devs) if num_devices is None else int(num_devices)
    if num_shards is None:
        if n % num_replicas:
            raise ValueError(
                f"{n} devices not divisible into {num_replicas} replica rows"
            )
        num_shards = n // num_replicas
    if num_replicas * num_shards != n:
        raise ValueError(
            f"mesh shape ({num_replicas} replicas x {num_shards} shards) "
            f"needs {num_replicas * num_shards} devices, have {n}"
        )
    if num_devices is None and hasattr(jax, "make_mesh"):
        return jax.make_mesh((num_replicas, num_shards), DATA_AXES)
    from jax.sharding import Mesh

    arr = np.asarray(devs[:n]).reshape(num_replicas, num_shards)
    return Mesh(arr, DATA_AXES)


def data_axes(mesh) -> Tuple[str, ...]:
    """The mesh axes that carry table rows, outermost first.

    ``(SEG_AXIS,)`` for the legacy 1-D mesh, ``(REPLICA_AXIS, SHARD_AXIS)``
    for the 2-D mesh — i.e. every mesh axis, in mesh order.
    """
    return tuple(mesh.axis_names)


def replica_rows(mesh) -> List:
    """One 1-D SHARD_AXIS submesh per replica row of a 2-D mesh.

    Each row sees a disjoint device set, so per-row engines stage disjoint
    full data copies (device caches key on mesh identity) under their own
    residency budgets.  A 1-D mesh is its own single row.
    """
    from jax.sharding import Mesh

    names = tuple(mesh.axis_names)
    if len(names) == 1:
        return [mesh]
    if names != DATA_AXES:
        raise ValueError(f"expected axes {DATA_AXES}, mesh has {names}")
    return [
        Mesh(np.asarray(mesh.devices[r]), (SHARD_AXIS,))
        for r in range(mesh.devices.shape[0])
    ]


def combine_hierarchical(op: Callable, x, axes: AxisSpec):
    """Apply a collective reduction axis-by-axis, innermost first.

    For ``(REPLICA_AXIS, SHARD_AXIS)`` this reduces over SHARD_AXIS (ICI)
    first — collapsing each replica row to one partial — then once over
    REPLICA_AXIS, so the reduction that crosses host/DCN boundaries carries
    partial-table bytes.  Reducing axis-by-axis is value-equal to a single
    reduction over the axes tuple; the split only pins the network order.
    """
    for ax in reversed(normalize_axes(axes)):
        x = op(x, ax)
    return x


def psum_hierarchical(x, axes: AxisSpec):
    from jax import lax

    return combine_hierarchical(lax.psum, x, axes)


def psum_ordered(x, axes: AxisSpec):
    """Order-canonical sum: every device's partial, reduced in GLOBAL device
    order with one fixed-order reduction.

    Integer psum is exact under any association, but FLOAT partial sums are
    not: a flat 8-way psum and a shard-then-replica hierarchy differ by ulps,
    which would break the topology bit-parity contract (a 2x4 run must
    reproduce the 1-D mesh's float BITS).  So float "add" combines gather the
    partials instead — hierarchically, shard/ICI stage first, so the
    replica/DCN stage still moves per-row blocks of partial-table bytes —
    into a [num_devices, ...] array whose leading dim is global (row-major)
    device order on EVERY topology, then left-fold it with an EXPLICIT add
    chain.  Not jnp.sum: XLA pattern-matches all-gather+reduce back into an
    all-reduce whose internal order follows the mesh topology — the exact
    nondeterminism this function exists to kill.  A chain of dependent adds
    cannot be reassociated, so: same operand order + same association = same
    bits, mesh shape be damned.  Costs a transient num_devices x partial
    buffer per device; partials are group tables/scalars, not raw rows, so
    this stays small.
    """
    from jax import lax

    names = normalize_axes(axes)
    for ax in reversed(names):  # innermost/ICI first, like the psum hierarchy
        x = lax.all_gather(x, ax)  # prepends that axis's device dim
    # leading dims stack outermost-first after the loop -> row-major flatten
    # is global device order, identical for ("seg",), (2,4), (4,2), (8,1)
    x = x.reshape((-1,) + x.shape[len(names):])
    total = x[0]
    for i in range(1, x.shape[0]):
        total = total + x[i]
    return total


def pmin_hierarchical(x, axes: AxisSpec):
    from jax import lax

    return combine_hierarchical(lax.pmin, x, axes)


def pmax_hierarchical(x, axes: AxisSpec):
    from jax import lax

    return combine_hierarchical(lax.pmax, x, axes)
