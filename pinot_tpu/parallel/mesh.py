"""Device mesh helpers.

Reference parity: the scatter axis of Pinot's deployment — segments spread
over servers, replicas over replica-groups (SURVEY.md 2.5).  TPU-native form:
a jax.sharding.Mesh whose axes name the parallelism strategies:

  seg      - horizontal data partitioning (scatter-gather analog): shards of
             the stacked table, combined in-graph by psum over ICI.
  replica  - replica groups for QPS scaling: the same data resident on R
             sub-meshes; the router (cluster/broker) picks one per query.

A single-host v5e-8 gives an 8-wide "seg" axis; multi-host pods extend the
same mesh over DCN transparently through jax's global device view.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def default_mesh(axis: str = "seg", num_devices: Optional[int] = None):
    """1-D mesh over all (or the first N) local devices."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis,))


def replica_mesh(num_replicas: int, axis_seg: str = "seg", axis_rep: str = "replica"):
    """2-D (replica, seg) mesh: data replicated across axis_rep, sharded
    across axis_seg (the replica-group serving topology)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n = len(devs)
    if n % num_replicas:
        raise ValueError(f"{n} devices not divisible into {num_replicas} replicas")
    arr = np.asarray(devs).reshape(num_replicas, n // num_replicas)
    return Mesh(arr, (axis_rep, axis_seg))
