"""Admin tools (pinot-tools PinotAdministrator analog)."""
