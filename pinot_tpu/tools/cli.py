"""CLI: the PinotAdministrator command tree, python-m style.

Reference parity: pinot-tools/.../tools/admin/command/ (CreateSegment,
PostQuery, StartServiceManager/quickstart commands).

  python -m pinot_tpu.tools.cli create-segment --schema s.json --csv d.csv --out dir
  python -m pinot_tpu.tools.cli query --segments dir1 dir2 --sql "SELECT ..."
  python -m pinot_tpu.tools.cli serve --segments dir1 --port 8099
  python -m pinot_tpu.tools.cli quickstart
  python -m pinot_tpu.tools.cli lint [paths...]
  python -m pinot_tpu.tools.cli slow-queries --url http://127.0.0.1:8099
  python -m pinot_tpu.tools.cli admission --url http://127.0.0.1:8099
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List


def _load_schema(path: str):
    from pinot_tpu.spi.schema import Schema

    with open(path, "r", encoding="utf-8") as f:
        return Schema.from_dict(json.load(f))


def _load_config(path, name):
    from pinot_tpu.spi.config import TableConfig

    if path:
        with open(path, "r", encoding="utf-8") as f:
            return TableConfig.from_dict(json.load(f))
    return TableConfig(name=name)


def cmd_create_segment(args) -> int:
    from pinot_tpu.ingest import read_csv_columns
    from pinot_tpu.segment.builder import build_segment

    schema = _load_schema(args.schema)
    cfg = _load_config(args.table_config, schema.name)
    cols = read_csv_columns(args.csv, schema=schema, delimiter=args.delimiter)
    name = args.name or os.path.splitext(os.path.basename(args.csv))[0]
    seg = build_segment(schema, cols, name, table_config=cfg, output_dir=args.out)
    print(f"built segment {name}: {seg.num_docs} docs -> {args.out}")
    return 0


def _engine_for_segments(segment_dirs: List[str]):
    from pinot_tpu.query.engine import QueryEngine
    from pinot_tpu.segment.segment import ImmutableSegment

    eng = QueryEngine()
    for d in segment_dirs:
        seg = ImmutableSegment.load(d)
        if seg.table_name not in eng.tables:
            eng.register_table(seg.schema)
        eng.add_segment(seg.table_name, seg)
    return eng


def cmd_query(args) -> int:
    eng = _engine_for_segments(args.segments)
    res = eng.sql(args.sql)
    print("\t".join(res.columns))
    for row in res.rows:
        print("\t".join(str(v) for v in row))
    print(
        f"-- {len(res.rows)} rows, {res.stats.num_docs_scanned} docs scanned, "
        f"{res.stats.time_ms:.1f} ms",
        file=sys.stderr,
    )
    return 0


def cmd_serve(args) -> int:
    from pinot_tpu.cluster.rest import QueryServer

    eng = _engine_for_segments(args.segments)
    server = QueryServer(eng, port=args.port).start()
    print(f"query server listening on http://127.0.0.1:{server.port}/query/sql")
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_quickstart(args) -> int:
    """In-memory demo: build a table, run example queries (quickstart analog)."""
    import numpy as np

    from pinot_tpu.query.engine import QueryEngine

    eng = QueryEngine()
    eng.sql(
        "CREATE TABLE demo (city STRING, product STRING, amount DOUBLE METRIC, ts TIMESTAMP) "
        "WITH (invertedIndexColumns = 'city', timeColumnName = 'ts')"
    )
    rng = np.random.default_rng(7)
    n = 100_000
    from pinot_tpu.segment.builder import build_segment

    state = eng.table("demo")
    data = {
        "city": rng.choice(["sf", "nyc", "tokyo", "berlin"], n).astype(object),
        "product": rng.choice(["a", "b", "c"], n).astype(object),
        "amount": np.round(rng.random(n) * 100, 2),
        "ts": 1_700_000_000_000 + rng.integers(0, 30 * 86_400_000, n),
    }
    eng.add_segment("demo", build_segment(state.schema, data, "demo_0", table_config=state.config))
    for sql in [
        "SELECT COUNT(*) FROM demo",
        "SELECT city, SUM(amount) FROM demo GROUP BY city ORDER BY SUM(amount) DESC",
        "SELECT product, COUNT(*) FROM demo WHERE city = 'sf' GROUP BY product",
        "EXPLAIN PLAN FOR SELECT COUNT(*) FROM demo WHERE city = 'sf'",
    ]:
        print(f"\n> {sql}")
        res = eng.sql(sql)
        print("\t".join(res.columns))
        for row in res.rows:
            print("\t".join(str(v) for v in row))
    return 0


def cmd_slow_queries(args) -> int:
    """Print a serving broker/engine's recent-query ring (GET /debug/queries):
    newest first, one line per query, trace presence flagged."""
    import urllib.request

    url = args.url.rstrip("/") + f"/debug/queries?limit={args.limit}"
    with urllib.request.urlopen(url) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    entries = payload.get("queries", [])
    if args.json:
        print(json.dumps(entries, indent=2, default=str))
        return 0
    for e in entries:
        flags = []
        if e.get("error"):
            flags.append("ERROR")
        if e.get("partialResult"):
            flags.append("PARTIAL")
        if e.get("trace") is not None:
            flags.append("TRACED")
        print(
            f"{e.get('timeMs', 0):>10.3f} ms  rows={e.get('rows', 0):<8} "
            f"docs={e.get('numDocsScanned', 0):<10} qid={e.get('queryId')} "
            f"fp={e.get('planFingerprint')} {' '.join(flags)}  {e.get('sql', '')}"
        )
    print(f"-- {len(entries)} entr(y/ies)", file=sys.stderr)
    return 0


def cmd_admission(args) -> int:
    """Print a serving endpoint's overload-protection state (GET
    /debug/admission): pressure level, admission bucket, host-budget ledger,
    active queries, and the recent kill ring."""
    import urllib.request

    url = args.url.rstrip("/") + "/debug/admission"
    with urllib.request.urlopen(url) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    if args.json:
        print(json.dumps(payload, indent=2))
        return 0
    adm = payload.get("admission", {})
    host = payload.get("hostBudget", {})
    dog = payload.get("watchdog", {})
    print(f"pressure level : {payload.get('pressureLevel', 0)}")
    print(
        f"admission      : rate={adm.get('rate', 0):g} units/s "
        f"tokens={adm.get('tokens', 0):g}/{adm.get('burst', 0):g} "
        f"waiting={adm.get('waiting', 0)}/{adm.get('maxQueue', 0)}"
    )
    print(
        f"host budget    : {host.get('inUseBytes', 0) / 1e6:.1f} / "
        f"{host.get('budgetBytes', 0) / 1e6:.1f} MB in use "
        f"(peak {host.get('peakBytes', 0) / 1e6:.1f} MB, "
        f"{host.get('reservations', 0)} reservation(s))"
    )
    print(f"active queries : {dog.get('activeQueries', 0)}")
    kills = dog.get("kills", [])
    for k in kills:
        print(
            f"  killed {k.get('queryId')} after {k.get('elapsedMs', 0):.1f} ms "
            f"({k.get('reservedBytes', 0) / 1e6:.1f} MB reserved): {k.get('reason')}"
        )
    print(f"-- {len(kills)} kill record(s)", file=sys.stderr)
    return 0


def cmd_election(args) -> int:
    """Print a serving endpoint's coordinator-HA view (GET /debug/election):
    current leader plus per-candidate lease/epoch/role state."""
    import urllib.request

    url = args.url.rstrip("/") + "/debug/election"
    with urllib.request.urlopen(url) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    if args.json:
        print(json.dumps(payload, indent=2))
        return 0
    print(f"leader  : {payload.get('leader') or '(none)'}")
    for c in payload.get("candidates", []):
        lease = c.get("lease")
        if lease is None:
            held = "no lease on disk"
        else:
            held = (
                f"lease holder={lease.get('holder')} epoch={lease.get('epoch')} "
                f"expires in {lease.get('expiresIn_s', 0):g} s"
            )
        flags = " PAUSED" if c.get("paused") else ""
        print(
            f"  {c.get('node')}: role={c.get('role')} epoch={c.get('epoch')} "
            f"journalSeq={c.get('journalSeq', '-')} ttl={c.get('ttl_s', 0):g}s "
            f"[{held}]{flags}"
        )
    print(f"-- {len(payload.get('candidates', []))} candidate(s)", file=sys.stderr)
    return 0


def cmd_autopilot(args) -> int:
    """Print a serving endpoint's SLO-autopilot view (GET /debug/autopilot):
    knob values vs clamp bounds, last N controller decisions with the
    triggering signal, per-table SLO state, and the knobChanges/ladderWalks
    counters."""
    import urllib.request

    url = args.url.rstrip("/") + "/debug/autopilot"
    with urllib.request.urlopen(url) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    if args.json:
        print(json.dumps(payload, indent=2))
        return 0
    if payload.get("enabled"):
        print(
            f"autopilot : ON  slo={payload.get('sloMs', 0):g} ms "
            f"tick={payload.get('tickS', 0):g} s ticks={payload.get('ticks', 0)} "
            f"cooldown={payload.get('cooldown', 0)} "
            f"running={payload.get('running', False)}"
        )
        bound = payload.get("changeBound", {})
        print(
            f"changes   : {payload.get('knobChanges', 0)} knob change(s), "
            f"{payload.get('ladderWalks', 0)} ladder walk(s) "
            f"(bound {bound.get('maxChanges', '-')}/{bound.get('windowTicks', '-')} ticks)"
        )
    else:
        print("autopilot : OFF (registry view only)")
    for name, k in sorted(payload.get("knobs", {}).items()):
        mark = "*" if k.get("overridden") else " "
        print(
            f"  {mark}{name:<18} = {k.get('value', 0):g}  "
            f"[{k.get('lo', 0):g} .. {k.get('hi', 0):g}]  "
            f"initial={k.get('initial', 0):g} degrade={k.get('degrade')}"
        )
    splits = payload.get("splits", {})
    if splits:
        shares = " ".join(f"{t}={f:.2f}" for t, f in sorted(splits.items()))
        print(f"  residency splits: {shares}")
    for t, st in sorted(payload.get("tables", {}).items()):
        p99 = st.get("p99_ms")
        p99s = f"{p99:.1f} ms" if p99 is not None else "-"
        print(f"  table {t}: {st.get('state', '?')} p99={p99s} qps={st.get('qps', 0):g}")
    decisions = payload.get("decisions", [])
    n = max(0, int(getattr(args, "last", 0) or 0)) or 10
    for d in decisions[-n:]:
        knob = f" {d.get('knob')}: {d.get('from')} -> {d.get('to')}" if d.get("knob") else ""
        sig = d.get("signal", {})
        p99 = sig.get("p99_ms")
        p99s = f"{p99:.1f}" if p99 is not None else "-"
        print(
            f"  tick {d.get('tick'):>4} {d.get('action', ''):<16}{knob}  "
            f"(p99={p99s} ms qps={sig.get('qps', 0):g})"
        )
    print(f"-- {len(decisions)} decision(s) recorded", file=sys.stderr)
    return 0


def cmd_perf(args) -> int:
    """Perf observatory view + bench-regression gate.

    Without --check: print a serving endpoint's per-table/per-shape perf
    ledger (GET /debug/perf) — rows/s, bytes/s, roofline %, compile ms,
    plan-cache hit rate, QPS.

    With --check: compare the newest bench_history.jsonl record against the
    pinned baseline (utils/perf.check_regression) with a noise-aware
    threshold, exiting nonzero on a regression — the CI gate that turns
    BENCH files from write-only artifacts into enforcement."""
    from pinot_tpu.utils import perf as perf_mod

    if args.check:
        history = perf_mod.load_bench_history(args.history)
        if not history:
            print(f"perf gate: no usable records in {args.history}", file=sys.stderr)
            return 1
        latest = history[-1]
        try:
            with open(args.baseline, "r", encoding="utf-8") as f:
                baseline = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"perf gate: cannot read baseline {args.baseline}: {e}", file=sys.stderr)
            return 1
        verdict = perf_mod.check_regression(latest, baseline, threshold=args.threshold)
        if args.json:
            print(json.dumps(verdict, indent=2))
        else:
            for c in verdict["checks"]:
                mark = "ok  " if c["ok"] else "FAIL"
                print(
                    f"{mark} {c['metric']:<28} baseline={c['baseline']:<14g} "
                    f"latest={c['latest']:<14g} drop={c['drop_pct']:+.2f}%"
                )
            for r in verdict["reasons"]:
                print(f"FAIL {r}")
            status = "PASS" if verdict["ok"] else "REGRESSION"
            print(
                f"perf gate: {status} (allowed drop {verdict['allowed_drop'] * 100:.1f}%)",
                file=sys.stderr,
            )
        return 0 if verdict["ok"] else 1

    import urllib.request

    url = args.url.rstrip("/") + "/debug/perf"
    with urllib.request.urlopen(url) as resp:
        payload = json.loads(resp.read().decode("utf-8"))
    if args.json:
        print(json.dumps(payload, indent=2))
        return 0
    tables = payload.get("tables", {})
    for table, t in sorted(tables.items()):
        print(f"table {table}: {t.get('queries', 0)} quer(y/ies), qps={t.get('qps', 0):g}")
        for fp, sh in sorted(t.get("shapes", {}).items()):
            rps = sh.get("rowsPerSec", {})
            roof = sh.get("rooflinePct", {})
            hit = sh.get("planCacheHitRate")
            print(
                f"  shape {fp}: n={sh.get('queries', 0)} "
                f"rows/s last={rps.get('last', 0):g} mean={rps.get('mean', 0):g} "
                f"roofline last={roof.get('last', 0):g}% "
                f"compileMs={sh.get('compileMsTotal', 0):g} "
                f"cacheHit={'n/a' if hit is None else f'{hit:.0%}'} "
                f"qps={sh.get('qps', 0):g}"
            )
    for name, cs in sorted(payload.get("caches", {}).items()):
        print(f"cache {name}: {cs.get('entries', 0)} entries, {cs.get('bytes', 0)} bytes")
    print(f"-- {len(tables)} table(s)", file=sys.stderr)
    return 0


def cmd_lint(args) -> int:
    """Static lint: per-file rules (analysis/repo_lint.py) plus the
    interprocedural passes (analysis/engine.py — race detector + sync
    auditor with baseline.json) over the package tree; explicit paths run
    the per-file rules only.  Exit 1 when findings exist so CI gates on it."""
    from pinot_tpu.analysis.repo_lint import RULES, lint_paths

    stale = []
    baselined = 0
    if args.paths:
        findings = lint_paths(args.paths)
    else:
        from pinot_tpu.analysis.engine import run_project

        report = run_project()
        findings = report.findings
        stale = report.stale_baseline
        baselined = report.baselined
    if args.json:
        payload = {
            "findings": [f.to_dict() for f in findings],
            "count": len(findings),
            "baselined": baselined,
            "staleBaseline": stale,
            "rules": {r: RULES[r] for r in sorted({f.rule for f in findings})},
        }
        mc_ok = True
        if not args.paths:
            # one machine-readable gate: fold a small-budget model-check
            # sweep (clean models only — the mutation matrix lives under
            # `cli mc`) into the lint report
            from pinot_tpu.analysis.model_check import check_all

            mc = check_all(seed=0, max_schedules=8, mutations=False)
            mc_ok = mc["ok"]
            payload["modelCheck"] = mc
        print(json.dumps(payload, indent=2))
        return 1 if findings or stale or not mc_ok else 0
    for f in findings:
        print(f)
    for e in stale:
        print(f"stale baseline entry (fixed? delete it): {json.dumps(e)}")
    if findings and args.explain:
        print("\nrules:", file=sys.stderr)
        hit = {f.rule for f in findings}
        for rule in sorted(hit):
            print(f"  {rule}: {RULES.get(rule, '?')}", file=sys.stderr)
    suffix = f" ({baselined} baselined)" if baselined else ""
    print(f"{len(findings)} finding(s){suffix}", file=sys.stderr)
    return 1 if findings or stale else 0


def cmd_mc(args) -> int:
    """Deterministic-schedule concurrency model checker (analysis/
    model_check.py) over the registered protocol models.  Default run
    explores a seeded schedule budget per protocol; `--mutations` also
    requires every broken twin to be CAUGHT within the budget; `--replay
    trace.json` re-runs a captured failing schedule and verifies the
    failure reproduces bit-identically.  Exit 1 on any gate miss."""
    from pinot_tpu.analysis.model_check import check_all, load_trace, replay, save_trace

    if args.replay:
        trace = load_trace(args.replay)
        want = trace["failure"]
        got = replay(trace)
        identical = got is not None and all(
            got[k] == want[k] for k in ("kind", "detail", "step", "schedule")
        )
        if args.json:
            print(json.dumps({"trace": trace, "reproduced": got, "identical": identical}, indent=2))
        elif identical:
            print(
                f"reproduced {trace['protocol']}"
                + (f"[{trace['mutation']}]" if trace.get("mutation") else "")
                + f": {got['kind']} at step {got['step']} — {got['detail']}"
            )
        else:
            print(f"trace did NOT reproduce: wanted {want!r}, got {got!r}", file=sys.stderr)
        return 0 if identical else 1

    protocols = args.protocols.split(",") if args.protocols else None
    report = check_all(
        seed=args.seed,
        max_schedules=args.schedules,
        mutations=args.mutations,
        protocols=protocols,
    )
    failing = []  # (protocol, mutation, failure) — clean failures first
    for name, entry in sorted(report["protocols"].items()):
        if entry["failure"] is not None:
            failing.insert(0, (name, None, entry["failure"]))
        for mut, res in sorted(entry.get("mutations", {}).items()):
            if res["failure"] is not None:
                failing.append((name, mut, res["failure"]))
    if args.save_trace and failing:
        name, mut, failure = failing[0]
        save_trace({"protocol": name, "mutation": mut, "failure": failure}, args.save_trace)
        print(f"trace saved: {args.save_trace} ({name}{f'[{mut}]' if mut else ''})", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    for name, entry in sorted(report["protocols"].items()):
        status = "FAIL" if entry["failure"] else "ok"
        line = f"{name:10s} {status:4s} {entry['schedulesExplored']} schedule(s)"
        if entry["failure"]:
            f = entry["failure"]
            line += f" — {f['kind']} at step {f['step']}: {f['detail']}"
        print(line)
        for mut, res in sorted(entry.get("mutations", {}).items()):
            verdict = "caught" if res["caught"] else "MISSED"
            line = f"  twin {mut}: {verdict} ({res['schedulesExplored']} schedule(s))"
            if res["failure"]:
                f = res["failure"]
                line += f" — {f['kind']}: {f['detail']}"
            print(line)
    print(("all gates green" if report["ok"] else "GATE FAILED"), file=sys.stderr)
    return 0 if report["ok"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="pinot_tpu", description="pinot_tpu admin CLI")
    sub = p.add_subparsers(dest="command", required=True)

    c = sub.add_parser("create-segment", help="CSV -> immutable segment directory")
    c.add_argument("--schema", required=True)
    c.add_argument("--csv", required=True)
    c.add_argument("--out", required=True)
    c.add_argument("--table-config")
    c.add_argument("--name")
    c.add_argument("--delimiter", default=",")
    c.set_defaults(fn=cmd_create_segment)

    q = sub.add_parser("query", help="run SQL over segment directories")
    q.add_argument("--segments", nargs="+", required=True)
    q.add_argument("--sql", required=True)
    q.set_defaults(fn=cmd_query)

    s = sub.add_parser("serve", help="HTTP query endpoint over segment directories")
    s.add_argument("--segments", nargs="+", required=True)
    s.add_argument("--port", type=int, default=8099)
    s.set_defaults(fn=cmd_serve)

    qs = sub.add_parser("quickstart", help="in-memory demo table + example queries")
    qs.set_defaults(fn=cmd_quickstart)

    sq = sub.add_parser("slow-queries", help="print a serving endpoint's recent/slow query log")
    sq.add_argument("--url", default="http://127.0.0.1:8099", help="query server base URL")
    sq.add_argument("--limit", type=int, default=20)
    sq.add_argument("--json", action="store_true", help="dump raw entries as JSON")
    sq.set_defaults(fn=cmd_slow_queries)

    ad = sub.add_parser("admission", help="print a serving endpoint's overload-protection state")
    ad.add_argument("--url", default="http://127.0.0.1:8099", help="query server base URL")
    ad.add_argument("--json", action="store_true", help="dump the raw snapshot as JSON")
    ad.set_defaults(fn=cmd_admission)

    el = sub.add_parser("election", help="print a serving endpoint's coordinator-HA leadership view")
    el.add_argument("--url", default="http://127.0.0.1:8099", help="query server base URL")
    el.add_argument("--json", action="store_true", help="dump the raw snapshot as JSON")
    el.set_defaults(fn=cmd_election)

    ap = sub.add_parser("autopilot", help="print a serving endpoint's SLO-autopilot state")
    ap.add_argument("--url", default="http://127.0.0.1:8099", help="query server base URL")
    ap.add_argument("--last", type=int, default=10, help="controller decisions to print")
    ap.add_argument("--json", action="store_true", help="dump the raw snapshot as JSON")
    ap.set_defaults(fn=cmd_autopilot)

    pf = sub.add_parser("perf", help="perf ledger view + bench-regression gate")
    pf.add_argument("--url", default="http://127.0.0.1:8099", help="query server base URL")
    pf.add_argument("--json", action="store_true", help="dump the raw snapshot/verdict as JSON")
    pf.add_argument("--check", action="store_true", help="gate mode: compare bench history vs baseline")
    pf.add_argument("--history", default="bench_history.jsonl", help="bench history file (--check)")
    pf.add_argument("--baseline", default="BENCH_BASELINE.json", help="pinned baseline record (--check)")
    pf.add_argument("--threshold", type=float, default=None, help="override allowed fractional drop (--check)")
    pf.set_defaults(fn=cmd_perf)

    lt = sub.add_parser("lint", help="JAX-aware static lint over the pinot_tpu tree")
    lt.add_argument("paths", nargs="*", help="python files to lint (default: the installed package)")
    lt.add_argument("--explain", action="store_true", help="print rule descriptions for findings")
    lt.add_argument("--json", action="store_true", help="machine-readable findings report")
    lt.set_defaults(fn=cmd_lint)

    mc = sub.add_parser("mc", help="deterministic-schedule concurrency model checker over the serving protocols")
    mc.add_argument("--seed", type=int, default=0, help="base RNG seed (schedule i uses seed+i)")
    mc.add_argument("--schedules", type=int, default=25, help="schedules explored per protocol/twin")
    mc.add_argument("--mutations", action="store_true", help="also require every broken twin to be caught")
    mc.add_argument("--protocols", default="", help="comma-separated protocol subset (default: all)")
    mc.add_argument("--replay", default="", metavar="TRACE_JSON", help="replay a captured failing trace; exit 0 iff it reproduces bit-identically")
    mc.add_argument("--save-trace", default="", metavar="PATH", help="write the first failing clean-model trace as replayable JSON")
    mc.add_argument("--json", action="store_true", help="machine-readable report")
    mc.set_defaults(fn=cmd_mc)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
