"""Timeseries query engine (pinot-timeseries analog)."""
from pinot_tpu.timeseries.engine import (
    FetchNode,
    SeriesAggregateNode,
    TimeBuckets,
    TimeSeriesBlock,
    TimeSeriesEngine,
    TransformNode,
    parse_pipeline,
)

__all__ = [
    "FetchNode",
    "SeriesAggregateNode",
    "TimeBuckets",
    "TimeSeriesBlock",
    "TimeSeriesEngine",
    "TransformNode",
    "parse_pipeline",
]
