"""Timeseries engine: time-bucketed series over the SQL engine.

Reference parity: the pinot-timeseries SPI (pinot-timeseries/
pinot-timeseries-spi/.../tsdb/spi/ — TimeSeriesLogicalPlanner, TimeBuckets,
series blocks) with language plugins (M3QL) planned into a logical tree and
executed over the MSE runtime (TimeSeriesRequestHandler).

Re-design: the leaf fetch compiles to an ordinary SQL group-by whose time
dimension is the bucketed epoch — `GROUP BY tags, ts/step` rides the
existing expression-group-by device kernels — and the series operators
(sumSeries/avgSeries/maxSeries, scale/offset/shift-absent) are host numpy
over [num_buckets]-sized series.  The pipe language here is an M3QL-shaped
built-in; other languages implement plan() -> node tree (the SPI surface).
"""
from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclass(frozen=True)
class TimeBuckets:
    """Aligned evaluation window (TimeBuckets.java analog)."""

    start_ms: int
    step_ms: int
    num: int

    @property
    def end_ms(self) -> int:
        return self.start_ms + self.step_ms * self.num

    def bucket_of(self, ts_ms: int) -> int:
        return (int(ts_ms) - self.start_ms) // self.step_ms

    def timestamps(self) -> List[int]:
        return [self.start_ms + i * self.step_ms for i in range(self.num)]


@dataclass
class TimeSeriesBlock:
    """One operator's output: {tag tuple -> [num] values} (nan = no data)."""

    buckets: TimeBuckets
    tag_names: Tuple[str, ...]
    series: Dict[Tuple, np.ndarray]


# -- logical plan nodes (tsdb spi plan analog) ------------------------------
@dataclass
class FetchNode:
    table: str
    value_expr: str  # SQL expression aggregated per bucket, e.g. "v"
    agg: str = "sum"  # sum | count | min | max | avg
    filter_sql: str = ""  # SQL boolean expression
    group_tags: Tuple[str, ...] = ()
    time_column: str = "ts"


@dataclass
class SeriesAggregateNode:
    op: str  # sum | avg | max | min
    keep_tags: Tuple[str, ...] = ()
    child: object = None


@dataclass
class TransformNode:
    op: str  # scale | offset
    arg: float = 1.0
    child: object = None


class TimeSeriesEngine:
    """Executes a plan tree against any engine exposing .query(sql)."""

    def __init__(self, engine):
        self.engine = engine

    def execute(self, node, buckets: TimeBuckets) -> TimeSeriesBlock:
        if isinstance(node, FetchNode):
            return self._fetch(node, buckets)
        if isinstance(node, SeriesAggregateNode):
            return self._series_agg(node, self.execute(node.child, buckets))
        if isinstance(node, TransformNode):
            return self._transform(node, self.execute(node.child, buckets))
        raise TypeError(f"unknown plan node {type(node).__name__}")

    # -- leaf: SQL group-by over (tags, bucketed time) -------------------
    def _fetch(self, node: FetchNode, b: TimeBuckets) -> TimeSeriesBlock:
        tc = node.time_column
        # integer bucketing via arithmetic the expression group-by can bound:
        # (ts - start) - MOD(ts - start, step) is the bucket START offset
        off = f"({tc} - {b.start_ms})"
        bucket_expr = f"{off} - MOD({off}, {b.step_ms})"
        groups = list(node.group_tags) + [bucket_expr]
        where = f"{tc} >= {b.start_ms} AND {tc} < {b.end_ms}"
        if node.filter_sql:
            where = f"({node.filter_sql}) AND {where}"
        agg_sql = "COUNT(*)" if node.agg == "count" else f"{node.agg.upper()}({node.value_expr})"
        sql = (
            f"SELECT {', '.join(groups)}, {agg_sql} FROM {node.table} "
            f"WHERE {where} GROUP BY {', '.join(groups)} LIMIT 10000000"
        )
        res = self.engine.query(sql)
        nt = len(node.group_tags)
        series: Dict[Tuple, np.ndarray] = {}
        for row in res.rows:
            tags = tuple(row[:nt])
            arr = series.get(tags)
            if arr is None:
                arr = series[tags] = np.full(b.num, np.nan)
            bucket = int(row[nt]) // b.step_ms
            if 0 <= bucket < b.num:
                arr[bucket] = float(row[nt + 1])
        return TimeSeriesBlock(b, tuple(node.group_tags), series)

    # -- series combinators ----------------------------------------------
    @staticmethod
    def _series_agg(node: SeriesAggregateNode, block: TimeSeriesBlock) -> TimeSeriesBlock:
        keep_idx = [block.tag_names.index(t) for t in node.keep_tags]
        grouped: Dict[Tuple, List[np.ndarray]] = {}
        for tags, arr in block.series.items():
            key = tuple(tags[i] for i in keep_idx)
            grouped.setdefault(key, []).append(arr)
        out: Dict[Tuple, np.ndarray] = {}
        for key, arrs in grouped.items():
            m = np.vstack(arrs)
            with np.errstate(all="ignore"):
                if node.op == "sum":
                    vals = np.nansum(m, axis=0)
                    vals[np.all(np.isnan(m), axis=0)] = np.nan
                elif node.op == "avg":
                    vals = np.nanmean(m, axis=0)
                elif node.op == "max":
                    vals = np.nanmax(m, axis=0)
                else:
                    vals = np.nanmin(m, axis=0)
            out[key] = vals
        return TimeSeriesBlock(block.buckets, tuple(node.keep_tags), out)

    @staticmethod
    def _transform(node: TransformNode, block: TimeSeriesBlock) -> TimeSeriesBlock:
        f = (lambda a: a * node.arg) if node.op == "scale" else (lambda a: a + node.arg)
        return TimeSeriesBlock(
            block.buckets, block.tag_names, {k: f(v) for k, v in block.series.items()}
        )


# -- built-in pipe language (M3QL-shaped) -----------------------------------
_FETCH_RX = re.compile(r"(\w+)\s*=\s*(?:'([^']*)'|\"([^\"]*)\"|(\S+))")


def parse_pipeline(text: str):
    """`fetch table=t value=v agg=sum filter='...' tags=city,dept time=ts
        | sumSeries city | scale 2` -> plan tree (language-plugin analog)."""
    stages = [s.strip() for s in text.split("|") if s.strip()]
    if not stages or not stages[0].startswith("fetch"):
        raise ValueError("pipeline must start with `fetch`")
    kv = {m.group(1): (m.group(2) or m.group(3) or m.group(4)) for m in _FETCH_RX.finditer(stages[0][5:])}
    if "table" not in kv or "value" not in kv:
        raise ValueError("fetch needs table= and value=")
    node: object = FetchNode(
        table=kv["table"],
        value_expr=kv["value"],
        agg=kv.get("agg", "sum"),
        filter_sql=kv.get("filter", ""),
        group_tags=tuple(t for t in kv.get("tags", "").split(",") if t),
        time_column=kv.get("time", "ts"),
    )
    for stage in stages[1:]:
        parts = stage.split()
        op = parts[0].lower()
        if op in ("sumseries", "avgseries", "maxseries", "minseries"):
            node = SeriesAggregateNode(op[:-6], tuple(parts[1:]), child=node)
        elif op in ("scale", "offset"):
            node = TransformNode(op, float(parts[1]), child=node)
        else:
            raise ValueError(f"unknown pipeline stage {op!r}")
    return node
