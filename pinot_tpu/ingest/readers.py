"""Input-format record readers: CSV (native C++ parse) and JSON lines.

Reference parity: pinot-plugins/pinot-input-format record readers (CSV,
JSON) feeding the segment builder.  Re-design: readers emit COLUMN-major
numpy arrays (what build_segment wants) instead of per-row GenericRow
objects; the CSV hot loop runs in native/csv.cc emitting field offsets, and
Python only slices + type-converts whole columns.
"""
from __future__ import annotations

import ctypes
import json
from typing import Any, Dict, List, Optional

import numpy as np

from pinot_tpu.spi.schema import DataType, Schema
from pinot_tpu.utils.native import get_lib


def read_csv_columns(
    path: str,
    columns: Optional[List[str]] = None,
    delimiter: str = ",",
    schema: Optional[Schema] = None,
) -> Dict[str, np.ndarray]:
    """CSV file -> {column: np array}, header row required."""
    with open(path, "rb") as f:
        data = f.read()
    header_end = data.find(b"\n")
    if header_end < 0:
        raise ValueError(f"{path}: no header row")
    header = [h.strip().strip('"') for h in data[:header_end].decode("utf-8").split(delimiter)]
    body = data[header_end + 1 :]
    ncols = len(header)

    fields = _parse_fields(body, delimiter, ncols)
    nrows = len(fields) // ncols
    want = columns or header
    out: Dict[str, np.ndarray] = {}
    for name in want:
        ci = header.index(name)
        vals = [fields[r * ncols + ci] for r in range(nrows)]
        out[name] = _typed(vals, schema.field(name).data_type if schema and name in schema else None)
    return out


def _parse_fields(body: bytes, delimiter: str, ncols: int) -> List[str]:
    lib = get_lib()
    if lib is not None:
        n_rows = lib.csv_count_rows(body, len(body))
        max_fields = int(n_rows) * ncols + ncols
        starts = np.empty(max_fields, dtype=np.int64)
        ends = np.empty(max_fields, dtype=np.int64)
        quoted = np.empty(max_fields, dtype=np.uint8)
        rows = lib.csv_parse(
            body,
            len(body),
            delimiter.encode("ascii"),
            ncols,
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ends.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            quoted.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            max_fields,
        )
        if rows >= 0:
            nf = int(rows) * ncols
            out = []
            for i in range(nf):
                s = body[starts[i] : ends[i]].decode("utf-8")
                if quoted[i]:
                    s = s.strip()
                    if s.startswith('"') and s.endswith('"'):
                        s = s[1:-1].replace('""', '"')
                out.append(s)
            return out
        # ragged/overflow: fall through to the python parser for the error
    import csv as _csv
    import io

    out = []
    for row in _csv.reader(io.StringIO(body.decode("utf-8")), delimiter=delimiter):
        if not row:
            continue
        if len(row) != ncols:
            raise ValueError(f"CSV row arity {len(row)} != header arity {ncols}: {row[:4]}...")
        out.extend(row)
    return out


def _typed(vals: List[str], dt: Optional[DataType]) -> np.ndarray:
    if dt is None:
        return np.asarray(vals, dtype=object)
    if dt.is_string_like:
        return np.asarray(vals, dtype=object)
    none_like = {"", "null", "NULL", "None"}
    if any(v in none_like for v in vals):
        return np.asarray([None if v in none_like else _scalar(v, dt) for v in vals], dtype=object)
    return np.asarray([_scalar(v, dt) for v in vals], dtype=dt.np_dtype)


def _scalar(v: str, dt: DataType):
    if dt in (DataType.INT, DataType.LONG, DataType.TIMESTAMP):
        return int(float(v)) if "." in v or "e" in v.lower() else int(v)
    if dt is DataType.BOOLEAN:
        return v.strip().lower() in ("1", "true", "t", "yes")
    return float(v)


class CsvRecordReader:
    """Row-oriented reader facade (stream-SPI/file ingestion input)."""

    def __init__(self, path: str, delimiter: str = ",", schema: Optional[Schema] = None):
        self.columns = read_csv_columns(path, delimiter=delimiter, schema=schema)
        self._n = len(next(iter(self.columns.values()))) if self.columns else 0

    def __len__(self) -> int:
        return self._n

    def __iter__(self):
        names = list(self.columns)
        for i in range(self._n):
            yield {n: self.columns[n][i] for n in names}


class JsonRecordReader:
    """JSON-lines reader (pinot-json input format analog)."""

    def __init__(self, path: str):
        self.rows: List[Dict[str, Any]] = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    self.rows.append(json.loads(line))

    def __len__(self) -> int:
        return len(self.rows)

    def __iter__(self):
        return iter(self.rows)

    def columns(self, names: List[str]) -> Dict[str, np.ndarray]:
        return {n: np.asarray([r.get(n) for r in self.rows], dtype=object) for n in names}
