"""Ingestion input formats (pinot-plugins/pinot-input-format analog)."""
from pinot_tpu.ingest.readers import CsvRecordReader, JsonRecordReader, read_csv_columns

__all__ = ["CsvRecordReader", "JsonRecordReader", "read_csv_columns"]
