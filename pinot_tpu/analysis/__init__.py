"""Static analysis: plan-time checks, repo lint, interprocedural passes.

Cooperating passes that enforce staging-time invariants BEFORE any JAX
tracing happens (DrJAX-style: MapReduce-shaped JAX programs stay fast
only when static shapes / stable dtypes / no host sync hold at trace
time):

  plan_check     - type/shape/dtype walker over the query IR; malformed
                   plans raise structured PlanCheckError instead of an
                   opaque tracer traceback from inside jax.jit.
  repo_lint      - per-file ast lint over the pinot_tpu tree for JAX
                   anti-patterns (W001-W008: weak-type float literals in
                   kernels, host<->device sync inside jitted code,
                   jit-in-loop recompilation, unlocked shared-state RMW,
                   wall-clock latency math, swallowed cluster
                   exceptions, unbounded metric names, literal-baked
                   plan-cache keys).
  engine         - interprocedural core: whole-package ASTs, symbol
                   table, import resolution, call graph (callgraph.py),
                   pass API, inline `# pinot-lint: disable=` handling
                   and the committed baseline (baseline.json).
  races          - lock-discipline race detector (W010 unguarded access
                   to lock-guarded attrs, W011 lock-order cycles, W012
                   blocking call while holding a lock).
  device_sync    - host-device sync auditor (W013 implicit device->host
                   syncs, W014 host branching on device values) on the
                   warm query path.
  compile_audit  - fingerprint -> compile-event recorder wrapped around
                   the kernel caches; counters exported via utils.metrics
                   and a guard that flags recompilation storms.
"""
from pinot_tpu.analysis.compile_audit import (
    DIST_AUDIT,
    MSE_AUDIT,
    SSE_AUDIT,
    CompileAudit,
    RecompilationStormError,
)
from pinot_tpu.analysis.engine import (
    AnalysisReport,
    Pass,
    Project,
    default_passes,
    load_baseline,
    run_passes,
    run_project,
)
from pinot_tpu.analysis.plan_check import PlanCheckError, PlanIssue, check_plan, collect_issues
from pinot_tpu.analysis.repo_lint import Finding, lint_paths, lint_source, lint_tree

__all__ = [
    "PlanCheckError",
    "PlanIssue",
    "check_plan",
    "collect_issues",
    "Finding",
    "lint_source",
    "lint_paths",
    "lint_tree",
    "AnalysisReport",
    "Pass",
    "Project",
    "default_passes",
    "load_baseline",
    "run_passes",
    "run_project",
    "CompileAudit",
    "RecompilationStormError",
    "SSE_AUDIT",
    "DIST_AUDIT",
    "MSE_AUDIT",
]
