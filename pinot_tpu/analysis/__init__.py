"""Static analysis: plan-time checks, repo lint, recompilation audit.

Three cooperating passes that enforce staging-time invariants BEFORE any
JAX tracing happens (DrJAX-style: MapReduce-shaped JAX programs stay fast
only when static shapes / stable dtypes / no host sync hold at trace time):

  plan_check     - type/shape/dtype walker over the query IR; malformed
                   plans raise structured PlanCheckError instead of an
                   opaque tracer traceback from inside jax.jit.
  repo_lint      - ast-based lint over the pinot_tpu tree for JAX
                   anti-patterns (weak-type float literals in kernels,
                   host<->device sync inside jitted code, jit-in-loop
                   recompilation, unlocked shared-state RMW in threaded
                   cluster classes).
  compile_audit  - fingerprint -> compile-event recorder wrapped around
                   the kernel caches; counters exported via utils.metrics
                   and a guard that flags recompilation storms.
"""
from pinot_tpu.analysis.compile_audit import (
    DIST_AUDIT,
    MSE_AUDIT,
    SSE_AUDIT,
    CompileAudit,
    RecompilationStormError,
)
from pinot_tpu.analysis.plan_check import PlanCheckError, PlanIssue, check_plan, collect_issues
from pinot_tpu.analysis.repo_lint import Finding, lint_paths, lint_source, lint_tree

__all__ = [
    "PlanCheckError",
    "PlanIssue",
    "check_plan",
    "collect_issues",
    "Finding",
    "lint_source",
    "lint_paths",
    "lint_tree",
    "CompileAudit",
    "RecompilationStormError",
    "SSE_AUDIT",
    "DIST_AUDIT",
    "MSE_AUDIT",
]
