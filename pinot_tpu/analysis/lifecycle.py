"""Resource-lifecycle passes: the static companion to the model checker.

W023 — paired-resource escape analysis.  The serving tier's ledgers hand
out resources through OPEN calls that a matching CLOSE must repay on every
path, including exception edges:

    ticket = self.budget.reserve(...)      ->  self.budget.release(ticket)
    ok     = self.budget.try_charge(n)     ->  self.budget.uncharge(n)
    hc.try_fire(opts)                      ->  hc.unfire()
    self.watchdog.register(qid)            ->  self.watchdog.deregister(qid)

A function that opens and does NOT let the handle ESCAPE (returned,
stored on self / into a container, or passed on to another owner) must
close on its exception edges: a matching close in a `finally` or an
`except` handler — lexically or through a project call chain that reaches
one (the r10 callgraph).  A close that only sits on the straight-line
path leaks the moment anything between open and close raises; no close at
all leaks on every path.  Escape means ownership moved — the pass stays
quiet and the dynamic checker (analysis/model_check.py) owns the proof
that the far end balances.

W024 — condition-variable discipline, the static face of the lost-wakeup
class the checker hunts dynamically:

  * `self.<cond>.wait()` must sit lexically inside a `while` loop — a
    woken waiter re-checks its predicate (spurious wakeups, stolen
    tokens); an `if` re-checks once and proceeds on stale truth.
  * `self.<cond>.notify()/notify_all()` must run while holding the
    condition's lock (ClassLockModel.locks_at) — a notify outside the
    lock races the waiter's predicate-check-then-park window, which is
    precisely a lost wakeup.

Both rules reuse the race-pass class model and the callgraph rather than
re-deriving lock regions.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from pinot_tpu.analysis.callgraph import CallGraph
from pinot_tpu.analysis.engine import FunctionInfo, Pass, Project
from pinot_tpu.analysis.races import build_class_model
from pinot_tpu.analysis.repo_lint import Finding

_COND_CTORS = {"threading.Condition", "pinot_tpu.utils.threads.Condition"}


@dataclass(frozen=True)
class ResourcePair:
    """One open/close family.  `receiver_hint` (substring of the receiver
    expression, lowercased) scopes noisy verb names to the ledger objects
    that actually follow the protocol."""

    openers: Tuple[str, ...]
    closers: Tuple[str, ...]
    receiver_hint: str = ""
    what: str = "resource"


RESOURCE_PAIRS: Tuple[ResourcePair, ...] = (
    ResourcePair(("reserve", "reserve_or_wait"), ("release",), "budget", "reservation"),
    ResourcePair(("try_charge",), ("uncharge",), "budget", "ledger charge"),
    ResourcePair(("try_fire",), ("unfire",), "", "hedge token"),
    ResourcePair(("register",), ("deregister",), "watchdog", "watchdog registration"),
    ResourcePair(("arm",), ("disarm",), "", "armed trigger"),
)


def _production(relpath: str) -> bool:
    """Lifecycle discipline binds production code; tests deliberately probe
    leak and crash paths (arming kill-points, reserving past the cap to
    assert ReservationError) and would drown the signal."""
    base = relpath.rsplit("/", 1)[-1]
    return not (
        relpath.startswith("tests/")
        or "/tests/" in relpath
        or base.startswith("test_")
        or base == "conftest.py"
    )


def _recv_text(node: ast.AST) -> Optional[str]:
    """Dotted receiver text for name/attribute chains ("self.budget",
    "hc"); None for anything fancier (calls, subscripts)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _recv_text(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _parents(fn: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for parent in ast.walk(fn):
        for child in ast.iter_child_nodes(parent):
            out[child] = parent
    return out


def _cleanup_spans(fn: ast.AST) -> List[Tuple[int, int]]:
    """Line spans of every `finally` block and `except` handler body in fn
    — the regions that run on exception edges."""
    spans: List[Tuple[int, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Try,)):
            for blk in (node.finalbody,):
                if blk:
                    end = getattr(blk[-1], "end_lineno", None) or blk[-1].lineno
                    spans.append((blk[0].lineno, end))
            for h in node.handlers:
                if h.body:
                    end = getattr(h.body[-1], "end_lineno", None) or h.body[-1].lineno
                    spans.append((h.body[0].lineno, end))
    return spans


def _in_spans(line: int, spans: List[Tuple[int, int]]) -> bool:
    return any(a <= line <= b for a, b in spans)


class LifecyclePass(Pass):
    """W023: an opened paired resource must escape or close on exception
    edges."""

    name = "lifecycle"

    def run(self, project: Project) -> List[Finding]:
        graph = CallGraph.build(project)
        closer_reach = self._closer_reachability(project, graph)
        findings: List[Finding] = []
        for fi in project.functions.values():
            if not _production(fi.module.relpath):
                continue
            findings.extend(self._check_function(project, graph, fi, closer_reach))
        return findings

    # -- interprocedural closer reachability ------------------------------

    def _closer_reachability(
        self, project: Project, graph: CallGraph
    ) -> Dict[str, Set[str]]:
        """qname -> closer attr names its body (transitively) calls."""
        all_closers = {c for p in RESOURCE_PAIRS for c in p.closers}
        direct: Dict[str, Set[str]] = {}
        for fi in project.functions.values():
            hit: Set[str] = set()
            for node in ast.walk(fi.node):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in all_closers
                ):
                    hit.add(node.func.attr)
            direct[fi.qname] = hit
        # fixpoint over call edges (the graphs are small; a few rounds)
        changed = True
        while changed:
            changed = False
            for caller in direct:
                for callee in graph.callees(caller):
                    extra = direct.get(callee, set()) - direct[caller]
                    if extra:
                        direct[caller] |= extra
                        changed = True
        return direct

    # -- per-function check ------------------------------------------------

    def _check_function(
        self,
        project: Project,
        graph: CallGraph,
        fi: FunctionInfo,
        closer_reach: Dict[str, Set[str]],
    ) -> List[Finding]:
        fn = fi.node
        parents = _parents(fn)
        spans = _cleanup_spans(fn)
        findings: List[Finding] = []
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            pair = self._pair_for(node.func.attr)
            if pair is None:
                continue
            recv = _recv_text(node.func.value)
            if recv is None:
                continue
            if pair.receiver_hint and pair.receiver_hint not in recv.lower():
                continue
            if self._defines_pair_method(fi, pair):
                continue  # the ledger's own implementation, not a client
            if self._escapes(fn, parents, node, pair):
                continue
            closer_lines = self._closer_lines(fn, pair, recv)
            cleanup_covers = any(_in_spans(ln, spans) for ln in closer_lines)
            if not cleanup_covers:
                cleanup_covers = self._cleanup_reaches_closer(
                    project, fi, pair, spans, closer_reach
                )
            if cleanup_covers:
                continue
            symbol = (
                f"{fi.cls.name}.{fi.name}" if fi.cls is not None else fi.name
            )
            if closer_lines:
                msg = (
                    f"{recv}.{node.func.attr}() opens a {pair.what} that "
                    f"{recv}.{pair.closers[0]}() repays only on the straight-line "
                    "path — an exception between them leaks it"
                )
                hint = f"move the {pair.closers[0]} into a finally: (or an except: unwind)"
            else:
                reach = closer_reach.get(fi.qname, set())
                if set(pair.closers) & reach:
                    continue  # closed somewhere down the call chain
                msg = (
                    f"{recv}.{node.func.attr}() opens a {pair.what} this function "
                    "never repays and never hands off"
                )
                hint = (
                    f"pair it with {recv}.{pair.closers[0]}() in a finally:, or "
                    "return/store the handle so the owner can"
                )
            findings.append(
                Finding(
                    fi.module.relpath,
                    node.lineno,
                    "W023",
                    msg,
                    hint=hint,
                    symbol=symbol,
                )
            )
        return findings

    @staticmethod
    def _pair_for(attr: str) -> Optional[ResourcePair]:
        for pair in RESOURCE_PAIRS:
            if attr in pair.openers:
                return pair
        return None

    @staticmethod
    def _defines_pair_method(fi: FunctionInfo, pair: ResourcePair) -> bool:
        """Calls inside the class that DEFINES the open/close protocol are
        the implementation (reserve_or_wait retrying reserve, release
        notifying) — lifecycle discipline binds the clients."""
        if fi.cls is None:
            return False
        names = set(fi.cls.methods)
        return bool(names & set(pair.openers)) and bool(names & set(pair.closers))

    # -- escape analysis ---------------------------------------------------

    def _escapes(
        self,
        fn: ast.AST,
        parents: Dict[ast.AST, ast.AST],
        call: ast.Call,
        pair: ResourcePair,
    ) -> bool:
        """True when the opened handle's ownership moves: returned, stored
        beyond a local, passed to another call, yielded, or bound into a
        structure.  Conservative toward quiet — W023 reports only handles
        that provably stay local."""
        parent = parents.get(call)
        # direct escape: return reserve(...), f(reserve(...)), yield ...,
        # self.t = reserve(...), d[k] = reserve(...), [reserve(...)], etc.
        if isinstance(parent, (ast.Return, ast.Yield, ast.Call, ast.Starred)):
            return True
        if isinstance(parent, (ast.Tuple, ast.List, ast.Dict, ast.Set)):
            return True
        if isinstance(parent, ast.keyword):
            return True
        binding: Optional[str] = None
        if isinstance(parent, ast.Assign):
            if len(parent.targets) == 1 and isinstance(parent.targets[0], ast.Name):
                binding = parent.targets[0].id
            else:
                return True  # self.attr = open(...) / a, b = ... — ownership moved
        elif isinstance(parent, ast.AnnAssign):
            if isinstance(parent.target, ast.Name):
                binding = parent.target.id
            else:
                return True
        elif isinstance(parent, (ast.Expr, ast.If, ast.While, ast.UnaryOp, ast.Compare, ast.BoolOp)):
            # bare statement / used as a predicate: nothing escaped
            binding = None
        elif parent is not None and not isinstance(parent, ast.stmt):
            # some other expression context (f-string, comparison chain...)
            return True
        if binding is None:
            return False
        # the bound local escapes if it is returned, passed to a call,
        # stored onto self / into a subscript, or re-exported any other way
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and _uses_name(node.value, binding):
                return True
            if isinstance(node, ast.Yield) and _uses_name(node.value, binding):
                return True
            if isinstance(node, ast.Call) and node is not call:
                # handing the handle BACK to its closer is repayment, not
                # an ownership transfer — every other callee is a new owner
                closes = (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in pair.closers
                )
                args = list(node.args) + [kw.value for kw in node.keywords]
                if not closes and any(_uses_name(a, binding) for a in args):
                    return True
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)) and _uses_name(
                        node.value, binding
                    ):
                        return True
        return False

    @staticmethod
    def _closer_lines(fn: ast.AST, pair: ResourcePair, recv: str) -> List[int]:
        lines: List[int] = []
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in pair.closers
                and _recv_text(node.func.value) == recv
            ):
                lines.append(node.lineno)
        return lines

    @staticmethod
    def _cleanup_reaches_closer(
        project: Project,
        fi: FunctionInfo,
        pair: ResourcePair,
        spans: List[Tuple[int, int]],
        closer_reach: Dict[str, Set[str]],
    ) -> bool:
        """A finally/except call into a project function that transitively
        closes the pair also covers the exception edge (grant.close(),
        self._finish(), ...)."""
        if not spans:
            return False
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Call) and _in_spans(node.lineno, spans)):
                continue
            target = project.resolve_call(fi, node)
            if target is None and isinstance(node.func, ast.Attribute):
                # unresolvable receiver (grant.close()): match by method name
                # over the whole project — coarse but sound for coverage
                mname = node.func.attr
                for qn, reach in closer_reach.items():
                    if qn.endswith(f".{mname}") and set(pair.closers) & reach:
                        return True
                continue
            if target is not None and set(pair.closers) & closer_reach.get(target, set()):
                return True
        return False


def _uses_name(node: Optional[ast.AST], name: str) -> bool:
    if node is None:
        return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == name:
            return True
    return False


class ConditionDisciplinePass(Pass):
    """W024: Condition.wait outside a while-predicate loop; notify without
    the condition's lock held."""

    name = "condition-discipline"

    def run(self, project: Project) -> List[Finding]:
        graph = CallGraph.build(project)
        findings: List[Finding] = []
        for ci in project.classes.values():
            if not _production(ci.module.relpath):
                continue
            cond_attrs = self._condition_attrs(project, ci)
            if not cond_attrs:
                continue
            model = build_class_model(project, ci, graph)
            for mname, mi in ci.methods.items():
                parents = _parents(mi.node)
                for node in ast.walk(mi.node):
                    if not (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                    ):
                        continue
                    recv = node.func.value
                    attr = (
                        recv.attr
                        if isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"
                        else None
                    )
                    if attr not in cond_attrs:
                        continue
                    if node.func.attr == "wait" and not self._inside_while(
                        node, parents, mi.node
                    ):
                        findings.append(
                            Finding(
                                ci.module.relpath,
                                node.lineno,
                                "W024",
                                f"self.{attr}.wait() in {ci.name}.{mname} is not "
                                "inside a while-predicate loop — a spurious or "
                                "stolen wakeup proceeds on a stale predicate",
                                hint="wrap the wait in `while not <predicate>:` "
                                "(re-check after every wake)",
                                symbol=f"{ci.name}.{mname}",
                            )
                        )
                    elif node.func.attr in ("notify", "notify_all"):
                        held = model.locks_at(mname, node.lineno)
                        if attr not in held:
                            findings.append(
                                Finding(
                                    ci.module.relpath,
                                    node.lineno,
                                    "W024",
                                    f"self.{attr}.{node.func.attr}() in "
                                    f"{ci.name}.{mname} without holding "
                                    f"self.{attr} — races the waiter's "
                                    "check-then-park window (lost wakeup)",
                                    hint=f"notify inside `with self.{attr}:`",
                                    symbol=f"{ci.name}.{mname}",
                                )
                            )
        return findings

    @staticmethod
    def _condition_attrs(project: Project, ci) -> Set[str]:
        out: Set[str] = set()
        for mi in ci.methods.values():
            for node in ast.walk(mi.node):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    continue
                if isinstance(node.value, ast.Call):
                    target = project.resolve_expr(mi, node.value.func)
                    if target in _COND_CTORS:
                        out.add(t.attr)
        return out

    @staticmethod
    def _inside_while(node: ast.AST, parents: Dict[ast.AST, ast.AST], fn: ast.AST) -> bool:
        cur = parents.get(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.While):
                return True
            cur = parents.get(cur)
        return False
