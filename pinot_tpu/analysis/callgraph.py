"""Call graph over a Project (analysis/engine.py).

Edges connect project functions ("pkg.mod.Class.method" -> callee qname);
calls that resolve to names outside the project (time.sleep,
jax.numpy.sum, urllib.request.urlopen) are kept separately in
`external` — the race and sync passes classify those by dotted name.
Instantiating a project class adds an edge to its __init__ (so
"reachable from a threaded module" follows construction).

Each edge remembers its call-site lines: the deadlock and
blocking-under-lock rules report the line the cycle/block enters at,
not just the pair of functions.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pinot_tpu.analysis.engine import FunctionInfo, Project


@dataclass
class CallGraph:
    project: Project
    # caller qname -> {callee qname -> [call-site lines]}
    edges: Dict[str, Dict[str, List[int]]] = field(default_factory=dict)
    # caller qname -> {external dotted name -> [call-site lines]}
    external: Dict[str, Dict[str, List[int]]] = field(default_factory=dict)

    @classmethod
    def build(cls, project: Project) -> "CallGraph":
        g = cls(project)
        for fi in project.functions.values():
            g.edges[fi.qname] = {}
            g.external[fi.qname] = {}
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = project.resolve_call(fi, node)
                if target is None:
                    continue
                target = g._normalize(target)
                if target in project.functions:
                    g.edges[fi.qname].setdefault(target, []).append(node.lineno)
                else:
                    g.external[fi.qname].setdefault(target, []).append(node.lineno)
        return g

    def _normalize(self, target: str) -> str:
        """Class instantiation -> its __init__ when the project defines one."""
        if target in self.project.classes:
            init = f"{target}.__init__"
            if init in self.project.functions:
                return init
        return target

    # -- queries ----------------------------------------------------------

    def callees(self, qname: str) -> Iterable[str]:
        return self.edges.get(qname, {})

    def call_sites(self, caller: str, callee: str) -> List[int]:
        return self.edges.get(caller, {}).get(callee, [])

    def reachable_from(self, roots: Iterable[str]) -> Set[str]:
        """Transitive closure of project functions reachable from roots."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.edges]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(c for c in self.edges.get(cur, {}) if c not in seen)
        return seen

    def transitive_external(self, qname: str, _seen: Optional[Set[str]] = None) -> Set[str]:
        """External dotted names reachable from qname (through project
        calls) — used to decide whether a call chain ends in a blocker."""
        seen = _seen if _seen is not None else set()
        if qname in seen:
            return set()
        seen.add(qname)
        out = set(self.external.get(qname, {}))
        for callee in self.edges.get(qname, {}):
            out |= self.transitive_external(callee, seen)
        return out


def function_lines(fi: FunctionInfo) -> Tuple[int, int]:
    """(start, end) line span of a function body."""
    end = getattr(fi.node, "end_lineno", None) or fi.node.lineno
    return fi.node.lineno, end
