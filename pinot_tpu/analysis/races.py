"""Lock-discipline race detector (W010/W011/W012) over a Project.

Guard inference is per class: an attribute written under `with
self.<lock>:` in any method is lock-guarded, and every other read or
write of it must hold the same lock (W010).  Three refinements keep the
repo's real conventions from flooding the report:

  * `__init__` is construction context — single-threaded by contract —
    and so are private helpers whose only call sites are `__init__`
    (e.g. realtime manager `_recover_partition`).
  * a method whose every project-wide call site sits inside a locked
    region of the same class is a "locked method" (`*_locked`
    convention: `_evict_locked`, `_publish_size_locked`); its whole body
    counts as holding that lock.
  * only classes reachable from threaded contexts are checked: classes
    in the modules that import `threading`, plus anything their
    functions (REST/scatter handlers included) transitively call.

W011 builds a lock-order graph — node (class, lock attr), edge when a
locked region transitively reaches another acquisition — and reports
strongly-connected components (ABBA deadlocks) plus same-lock
re-acquisition through a call chain when the lock is a non-reentrant
`threading.Lock` (self-deadlock).

W012 flags calls that can block the lock holder: `time.sleep`, device
puts/gets, `.block_until_ready()`, socket/HTTP — directly in a locked
region or through a project call chain.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pinot_tpu.analysis.callgraph import CallGraph
from pinot_tpu.analysis.engine import ClassInfo, FunctionInfo, Pass, Project
from pinot_tpu.analysis.repo_lint import Finding

BLOCKING_EXTERNAL = {
    "time.sleep",
    "jax.device_put",
    "jax.device_get",
    "jax.block_until_ready",
    "urllib.request.urlopen",
    "socket.create_connection",
    "socket.socket",
}
BLOCKING_ATTRS = {"block_until_ready", "urlopen", "recv", "sendall", "connect", "getresponse"}

_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "add", "discard", "update", "setdefault", "move_to_end", "appendleft",
}

_NON_REENTRANT_CTORS = {"threading.Lock", "pinot_tpu.utils.threads.Lock"}
_REENTRANT_CTORS = {
    "threading.RLock",
    "threading.Condition",
    "pinot_tpu.utils.threads.RLock",
    "pinot_tpu.utils.threads.Condition",
}


def _self_attr_name(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


@dataclass
class LockRegion:
    lock: str
    start: int
    end: int

    def covers(self, line: int) -> bool:
        return self.start <= line <= self.end


@dataclass
class ClassLockModel:
    """Everything the three rules need to know about one class."""

    info: ClassInfo
    lock_attrs: Dict[str, Optional[bool]] = field(default_factory=dict)  # name -> reentrant?
    regions: Dict[str, List[LockRegion]] = field(default_factory=dict)   # method -> regions
    locked_methods: Dict[str, Set[str]] = field(default_factory=dict)    # method -> held locks
    init_only: Set[str] = field(default_factory=set)
    guards: Dict[str, Set[str]] = field(default_factory=dict)            # attr -> guarding locks

    def locks_at(self, method: str, line: int) -> Set[str]:
        held = set(self.locked_methods.get(method, ()))
        for r in self.regions.get(method, ()):
            if r.covers(line):
                held.add(r.lock)
        return held


def _ctor_reentrancy(project: Project, fi: FunctionInfo, value: ast.AST) -> Optional[bool]:
    if not isinstance(value, ast.Call):
        return None
    target = project.resolve_expr(fi, value.func)
    if target in _NON_REENTRANT_CTORS:
        return False
    if target in _REENTRANT_CTORS:
        return True
    return None


def build_class_model(project: Project, ci: ClassInfo, graph: CallGraph) -> ClassLockModel:
    model = ClassLockModel(ci)

    # lock attrs: `self.X = threading.Lock()/RLock()/Condition()` in __init__,
    # plus anything used as `with self.X:` whose name mentions "lock"/"cond"
    init = ci.methods.get("__init__")
    if init is not None:
        for node in ast.walk(init.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                attr = _self_attr_name(node.targets[0])
                if attr is None:
                    continue
                reentrant = _ctor_reentrancy(project, init, node.value)
                if reentrant is not None:
                    model.lock_attrs[attr] = reentrant

    for mname, mi in ci.methods.items():
        regions: List[LockRegion] = []
        for node in ast.walk(mi.node):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                attr = _self_attr_name(item.context_expr)
                if attr is None:
                    continue
                known = attr in model.lock_attrs
                if known or "lock" in attr.lower() or "cond" in attr.lower():
                    model.lock_attrs.setdefault(attr, None)
                    end = getattr(node, "end_lineno", None) or node.lineno
                    regions.append(LockRegion(attr, node.lineno, end))
        if regions:
            model.regions[mname] = regions

    _infer_calling_contexts(model, graph)
    _infer_guards(model)
    return model


def _intra_call_sites(model: ClassLockModel) -> Dict[str, List[Tuple[str, int]]]:
    """callee method name -> [(caller method name, line)] for self.m() calls."""
    sites: Dict[str, List[Tuple[str, int]]] = {}
    for caller, fi in model.info.methods.items():
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                attr = _self_attr_name(node.func)
                if attr in model.info.methods:
                    sites.setdefault(attr, []).append((caller, node.lineno))
    return sites


def _has_external_callers(model: ClassLockModel, method: str, graph: CallGraph) -> bool:
    qname = model.info.methods[method].qname
    prefix = model.info.qname + "."
    for caller, callees in graph.edges.items():
        if qname in callees and not caller.startswith(prefix):
            return True
    return False


def _infer_calling_contexts(model: ClassLockModel, graph: CallGraph) -> None:
    """Fixpoint over two facts: a method called only from __init__ chains is
    construction context; a method whose every call site holds lock L runs
    under L."""
    sites = _intra_call_sites(model)

    candidates = {
        m for m in model.info.methods
        if m != "__init__"
        and m in sites
        and not _has_external_callers(model, m, graph)
    }

    changed = True
    while changed:
        changed = False
        for m in candidates:
            if m.startswith("_") and m not in model.init_only:
                callers = {c for c, _ in sites[m]}
                if callers and all(
                    c == "__init__" or c in model.init_only for c in callers
                ):
                    model.init_only.add(m)
                    changed = True
            if m not in model.locked_methods:
                held_everywhere: Optional[Set[str]] = None
                for caller, line in sites[m]:
                    held = model.locks_at(caller, line)
                    held_everywhere = held if held_everywhere is None else held_everywhere & held
                if held_everywhere:
                    model.locked_methods[m] = held_everywhere
                    changed = True
    model.init_only -= set(model.locked_methods)


def _attr_writes(fn: ast.AST):
    """Yield (attr, line) for writes/mutations of self.<attr> in fn."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                attr = _self_attr_name(t)
                if attr is not None:
                    yield attr, t.lineno
                if isinstance(t, ast.Subscript):
                    attr = _self_attr_name(t.value)
                    if attr is not None:
                        yield attr, t.lineno
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _MUTATING_METHODS:
                attr = _self_attr_name(node.func.value)
                if attr is not None:
                    yield attr, node.lineno
        elif isinstance(node, (ast.Delete,)):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                attr = _self_attr_name(base)
                if attr is not None:
                    yield attr, t.lineno


def _infer_guards(model: ClassLockModel) -> None:
    for mname, mi in model.info.methods.items():
        if mname == "__init__" or mname in model.init_only:
            continue
        for attr, line in _attr_writes(mi.node):
            if attr in model.lock_attrs:
                continue
            held = model.locks_at(mname, line)
            if held:
                model.guards.setdefault(attr, set()).update(held)


class RacePass(Pass):
    name = "races"

    def __init__(self, check_all_classes: bool = False) -> None:
        # check_all_classes drops the threaded-reachability restriction —
        # fixture packages that don't import threading can still exercise
        # the rules.
        self.check_all_classes = check_all_classes

    # -- scope -------------------------------------------------------------

    def _threaded_classes(self, project: Project, graph: CallGraph) -> Set[str]:
        if self.check_all_classes:
            return set(project.classes)
        roots = [
            fi.qname
            for fi in project.functions.values()
            if fi.module.threaded
            or fi.module.relpath.endswith(("cluster/rest.py", "cluster/broker.py"))
        ]
        reach = graph.reachable_from(roots)
        out: Set[str] = set()
        for cq, ci in project.classes.items():
            if ci.module.threaded or any(m.qname in reach for m in ci.methods.values()):
                out.add(cq)
        return out

    # -- entry -------------------------------------------------------------

    def run(self, project: Project) -> List[Finding]:
        graph = CallGraph.build(project)
        threaded = self._threaded_classes(project, graph)
        models: Dict[str, ClassLockModel] = {}
        for cq in threaded:
            ci = project.classes[cq]
            model = build_class_model(project, ci, graph)
            if model.lock_attrs:
                models[cq] = model

        findings: List[Finding] = []
        for model in models.values():
            findings.extend(self._check_w010(model))
        findings.extend(self._check_w011(project, graph, models))
        findings.extend(self._check_w012(project, graph, models))
        return findings

    # -- W010: unguarded access to a lock-guarded attribute ----------------

    def _check_w010(self, model: ClassLockModel) -> List[Finding]:
        findings: List[Finding] = []
        ci = model.info
        reported: Set[Tuple[str, str]] = set()
        for mname, mi in ci.methods.items():
            if mname == "__init__" or mname in model.init_only:
                continue
            for node in ast.walk(mi.node):
                attr = _self_attr_name(node)
                if attr is None or attr not in model.guards:
                    continue
                held = model.locks_at(mname, node.lineno)
                if held & model.guards[attr]:
                    continue
                key = (mname, attr)
                if key in reported:
                    continue
                reported.add(key)
                lock = sorted(model.guards[attr])[0]
                kind = "written" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
                findings.append(
                    Finding(
                        ci.module.relpath,
                        node.lineno,
                        "W010",
                        f"self.{attr} is guarded by self.{lock} elsewhere in "
                        f"{ci.name} but {kind} without it in {ci.name}.{mname}",
                        hint=f"acquire self.{lock} (or snapshot the value under it)",
                        symbol=f"{ci.name}.{mname}",
                    )
                )
        return findings

    # -- W011: lock-order cycles -------------------------------------------

    def _acquires_closure(
        self,
        qname: str,
        models: Dict[str, ClassLockModel],
        project: Project,
        graph: CallGraph,
        memo: Dict[str, Set[Tuple[str, str]]],
        stack: Set[str],
    ) -> Set[Tuple[str, str]]:
        if qname in memo:
            return memo[qname]
        if qname in stack:
            return set()
        stack.add(qname)
        out: Set[Tuple[str, str]] = set()
        fi = project.functions.get(qname)
        if fi is not None and fi.cls is not None and fi.cls.qname in models:
            model = models[fi.cls.qname]
            for r in model.regions.get(fi.name, ()):
                out.add((fi.cls.qname, r.lock))
        for callee in graph.callees(qname):
            out |= self._acquires_closure(callee, models, project, graph, memo, stack)
        stack.discard(qname)
        memo[qname] = out
        return out

    def _check_w011(
        self, project: Project, graph: CallGraph, models: Dict[str, ClassLockModel]
    ) -> List[Finding]:
        findings: List[Finding] = []
        memo: Dict[str, Set[Tuple[str, str]]] = {}
        # edges[(C, L1)] -> {(D, L2): (relpath, line, via)}
        edges: Dict[Tuple[str, str], Dict[Tuple[str, str], Tuple[str, int, str]]] = {}

        for cq, model in models.items():
            for mname, regions in model.regions.items():
                fi = model.info.methods[mname]
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    target = project.resolve_call(fi, node)
                    if target is None or target not in project.functions:
                        continue
                    held_here = [r for r in regions if r.covers(node.lineno)]
                    if not held_here:
                        continue
                    acquired = self._acquires_closure(
                        target, models, project, graph, memo, set()
                    )
                    for r in held_here:
                        src = (cq, r.lock)
                        for dst in acquired:
                            if dst == src:
                                if models[cq].lock_attrs.get(r.lock) is False:
                                    findings.append(
                                        Finding(
                                            model.info.module.relpath,
                                            node.lineno,
                                            "W011",
                                            f"{model.info.name}.{mname} holds "
                                            f"self.{r.lock} (non-reentrant Lock) and the "
                                            f"call chain through {_short(target)} "
                                            f"re-acquires it — self-deadlock",
                                            hint="use threading.RLock or hoist the call "
                                            "out of the locked region",
                                            symbol=f"{model.info.name}.{mname}",
                                        )
                                    )
                                continue
                            edges.setdefault(src, {}).setdefault(
                                dst,
                                (model.info.module.relpath, node.lineno, _short(target)),
                            )
                # syntactically nested regions also order locks
                ordered = sorted(regions, key=lambda r: (r.start, -r.end))
                for outer in ordered:
                    for inner in ordered:
                        if inner is outer or not outer.covers(inner.start):
                            continue
                        if inner.lock != outer.lock:
                            edges.setdefault((cq, outer.lock), {}).setdefault(
                                (cq, inner.lock),
                                (model.info.module.relpath, inner.start, "nested with"),
                            )

        findings.extend(self._cycles(edges, models))
        return findings

    def _cycles(self, edges, models) -> List[Finding]:
        findings: List[Finding] = []
        reported: Set[frozenset] = set()

        def reaches(src, dst) -> bool:
            seen, stack = set(), [src]
            while stack:
                cur = stack.pop()
                if cur == dst:
                    return True
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(edges.get(cur, {}))
            return False

        for src, dsts in edges.items():
            for dst, (relpath, line, via) in dsts.items():
                if not reaches(dst, src):
                    continue
                cyc = frozenset((src, dst))
                if cyc in reported:
                    continue
                reported.add(cyc)
                a = f"{_short(src[0])}.{src[1]}"
                b = f"{_short(dst[0])}.{dst[1]}"
                findings.append(
                    Finding(
                        relpath,
                        line,
                        "W011",
                        f"lock-order cycle: {a} -> {b} (via {via}) and {b} -> {a} "
                        "elsewhere — two threads can deadlock",
                        hint="pick one global acquisition order or narrow one "
                        "region to drop the nested acquire",
                        symbol=a,
                    )
                )
        return findings

    # -- W012: blocking calls while holding a lock -------------------------

    def _blocking_closure(
        self, qname: str, graph: CallGraph, memo: Dict[str, Optional[str]], stack: Set[str]
    ) -> Optional[str]:
        """Name of a blocker reachable from qname (through project calls)."""
        if qname in memo:
            return memo[qname]
        if qname in stack:
            return None
        stack.add(qname)
        result: Optional[str] = None
        for ext in graph.external.get(qname, {}):
            if ext in BLOCKING_EXTERNAL:
                result = ext
                break
        if result is None:
            fi = graph.project.functions.get(qname)
            if fi is not None:
                blocker = _direct_attr_blocker(fi.node)
                if blocker:
                    result = blocker
        if result is None:
            for callee in graph.callees(qname):
                result = self._blocking_closure(callee, graph, memo, stack)
                if result:
                    result = f"{_short(callee)} -> {result}"
                    break
        stack.discard(qname)
        memo[qname] = result
        return result

    def _check_w012(
        self, project: Project, graph: CallGraph, models: Dict[str, ClassLockModel]
    ) -> List[Finding]:
        findings: List[Finding] = []
        memo: Dict[str, Optional[str]] = {}
        for cq, model in models.items():
            ci = model.info
            for mname, fi in ci.methods.items():
                regions = model.regions.get(mname, [])
                always_held = model.locked_methods.get(mname, set())
                if not regions and not always_held:
                    continue
                reported: Set[Tuple[int, str]] = set()
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    held = set(always_held)
                    held.update(r.lock for r in regions if r.covers(node.lineno))
                    if not held:
                        continue
                    lock = sorted(held)[0]
                    blocker: Optional[str] = None
                    target = project.resolve_call(fi, node)
                    if target is not None and target in BLOCKING_EXTERNAL:
                        blocker = target
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in BLOCKING_ATTRS
                        and not isinstance(node.func.value, ast.Constant)
                    ):
                        blocker = f".{node.func.attr}()"
                    elif target is not None and target in project.functions:
                        chain = self._blocking_closure(target, graph, memo, set())
                        if chain:
                            blocker = f"{_short(target)} -> {chain}"
                    if blocker is None:
                        continue
                    key = (node.lineno, blocker)
                    if key in reported:
                        continue
                    reported.add(key)
                    findings.append(
                        Finding(
                            ci.module.relpath,
                            node.lineno,
                            "W012",
                            f"{blocker} can block while {ci.name}.{mname} holds "
                            f"self.{lock}",
                            hint="move the blocking call outside the locked region "
                            "(stage under the lock, act after release)",
                            symbol=f"{ci.name}.{mname}",
                        )
                    )
        return findings


def _direct_attr_blocker(fn: ast.AST) -> Optional[str]:
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in BLOCKING_ATTRS
            and not isinstance(node.func.value, ast.Constant)
        ):
            return f".{node.func.attr}()"
    return None


def _short(qname: str) -> str:
    parts = qname.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else qname
