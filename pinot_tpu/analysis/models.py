"""Checked concurrency models for the serving-tier protocols.

Each model wraps the REAL protocol class (not a re-implementation) in a
small closed-world scenario: a handful of threads exercising the exact
code paths production takes, with the threading primitives supplied by the
deterministic scheduler through the `utils/threads` seam.  The model
declares the protocol's correctness argument as executable invariants:

  residency — single staging owner per group; the raw and `#packed`
      flavors of a group publish/evict atomically (never observably
      mixed); the ResourceBudget ledger balances on EVERY path including
      `abort_stage` (a mid-stage crash leaves no leaked charge).
  admission — `ResourceBudget.reserve_or_wait` never overcommits the
      byte budget, and parked staged-fetch waiters are always woken or
      timed out (no lost wakeups).
  batcher — every submitted future settles exactly once (no lost and no
      double-settled futures across the flush / full-group / runner-crash
      races).
  lease — at most one epoch appends to the journal at a time: epochs in
      the journal never decrease, and a deposed writer is always fenced
      before its stale append lands.
  knobs — the autopilot KnobRegistry's one-tick-one-swap contract: a
      query reading `view()` concurrently with a controller `set_many`
      tick sees either the whole tick or none of it, never a mid-tick
      mix of old and new knob values.

Every model also ships MUTATIONS: deliberately broken twins (the bug the
invariant exists to catch, reintroduced surgically).  `check_all(...,
mutations=True)` must catch every one within the gate's schedule budget —
that is the checker's own regression test, in the TP/clean-negative style
of test_analysis_races.py.

Model-thread code may use provider primitives freely; invariant callbacks
run on the harness thread between steps and read protocol state RAW
(plain attribute reads, no locks — every model thread is parked when they
run).
"""
from __future__ import annotations

import shutil
import tempfile
from typing import Any, Callable, Dict, List, Optional, Tuple

from pinot_tpu.utils import threads


class _InjectedCrash(RuntimeError):
    """The fault a crash-path scenario injects into its owner thread."""


class BaseModel:
    name = "base"
    MUTATIONS: Tuple[str, ...] = ()

    def __init__(self, mutation: Optional[str] = None):
        if mutation is not None and mutation not in self.MUTATIONS:
            raise ValueError(f"{self.name}: unknown mutation {mutation!r}")
        self.mutation = mutation

    def setup(self) -> None:  # pragma: no cover - interface default
        pass

    def teardown(self) -> None:
        pass

    def threads(self) -> List[Tuple[str, Callable[[], None]]]:
        raise NotImplementedError

    def invariants(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        return []

    def at_quiescence(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        return []


# ---------------------------------------------------------------------------
# residency: single staging owner, atomic flavor publish/evict, ledger balance
# ---------------------------------------------------------------------------
class _Device:
    """Stand-in for a segment cache's device table: both flavors of a group
    live and die together under ONE critical section of `lock` — exactly
    the contract the r17 satellite fix established."""

    def __init__(self, broken_evict: bool = False):
        self.lock = threads.Lock()
        self.slots: Dict[Tuple, int] = {}  # (group, flavor) -> nbytes
        self.broken_evict = broken_evict

    def put(self, group: Tuple, nbytes: int) -> None:
        with self.lock:
            self.slots[(group, "raw")] = nbytes // 2
            self.slots[(group, "packed")] = nbytes - nbytes // 2

    def drop(self, group: Tuple) -> None:
        if self.broken_evict:
            # MUTATION: flavors cleared one at a time with no lock — a
            # reader between the pops observes half a group
            self.slots.pop((group, "raw"), None)  # pinot-lint: disable=W010
            threads.checkpoint()
            self.slots.pop((group, "packed"), None)
        else:
            with self.lock:
                self.slots.pop((group, "raw"), None)
                self.slots.pop((group, "packed"), None)

    def group_bytes(self) -> int:
        return sum(self.slots.values())  # pinot-lint: disable=W010


class ResidencyModel(BaseModel):
    name = "residency"
    MUTATIONS = ("missing_uncharge_on_abort", "evict_outside_device_lock")

    BUDGET = 150

    def setup(self) -> None:
        from pinot_tpu.cluster.admission import ResourceBudget
        from pinot_tpu.segment.residency import ResidencyManager

        self.budget = ResourceBudget(self.BUDGET)
        rm_cls = ResidencyManager
        if self.mutation == "missing_uncharge_on_abort":
            rm_cls = _make_broken_residency()
        self.rm = rm_cls(self.budget, name="mc.residency")
        self.device = _Device(broken_evict=self.mutation == "evict_outside_device_lock")
        self.owners: Dict[Tuple, int] = {}  # group -> live staging owners
        self.sheds = 0

    def _stage(self, group: Tuple, table: str, nbytes: int, crash: bool = False) -> None:
        from pinot_tpu.cluster.admission import ReservationError
        from pinot_tpu.segment.residency import HIT, OWN, WAIT

        for _ in range(10):  # re-plan bound: transitions are finite
            status, entry = self.rm.begin_stage(
                group, table, evict_cb=lambda g=group: self.device.drop(g)
            )
            if status == HIT:
                return
            if status == WAIT:
                if not self.rm.wait(entry, timeout_s=20.0):
                    raise RuntimeError(f"stall timeout waiting for {group}")
                continue
            assert status == OWN
            self.owners[group] = self.owners.get(group, 0) + 1
            try:
                self.rm.charge(group, nbytes)
                threads.checkpoint()  # the host->device copy window
                if crash:
                    raise _InjectedCrash(f"mid-stage crash while staging {group}")
                self.device.put(group, nbytes)
                self.rm.finish_stage(group)
            except ReservationError:
                self.rm.abort_stage(group)  # cache full even after draining: shed
                self.sheds += 1
                return
            except _InjectedCrash:
                self.rm.abort_stage(group)  # the crash-path unwind under test
                return
            finally:
                self.owners[group] = self.owners.get(group, 1) - 1
            return
        raise RuntimeError(f"staging {group} did not settle within the re-plan bound")

    def threads(self) -> List[Tuple[str, Callable[[], None]]]:
        return [
            ("stage-A", lambda: self._stage(("segA", 0), "t1", 60)),
            ("stage-B", lambda: self._stage(("segB", 0), "t1", 60)),
            ("stage-C", lambda: self._stage(("segC", 0), "t2", 60)),
            ("crash-D", lambda: self._stage(("segD", 0), "t2", 10, crash=True)),
        ]

    def invariants(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def single_owner() -> Optional[str]:
            bad = {g: n for g, n in self.owners.items() if n > 1}
            return f"multiple staging owners: {bad}" if bad else None

        def ledger_bounded() -> Optional[str]:
            if self.budget._in_use > self.budget.budget_bytes:
                return (
                    f"ledger overcommitted: {self.budget._in_use} > "
                    f"{self.budget.budget_bytes}"
                )
            return None

        def flavors_paired() -> Optional[str]:
            groups = {g for (g, _f) in self.device.slots}
            for g in groups:
                have = {f for (gg, f) in self.device.slots if gg == g}
                if have != {"raw", "packed"}:
                    return f"group {g} observed with mixed flavors: {sorted(have)}"
            return None

        return [
            ("single-staging-owner", single_owner),
            ("ledger-never-overcommits", ledger_bounded),
            ("flavors-publish-atomically", flavors_paired),
        ]

    def at_quiescence(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def ledger_balances() -> Optional[str]:
            resident = sum(e.nbytes for e in self.rm._entries.values())
            pending = sum(e.pending for e in self.rm._entries.values())
            if pending:
                return f"{pending} pending bytes left at quiescence"
            if self.budget._in_use != resident:
                return (
                    f"ledger leak: in_use={self.budget._in_use} but resident "
                    f"bytes total {resident} (abort/evict path lost an uncharge)"
                )
            if self.device.group_bytes() != resident:
                return (
                    f"device holds {self.device.group_bytes()} bytes but the "
                    f"manager accounts {resident}"
                )
            return None

        return [("ledger-balances-at-rest", ledger_balances)]


def _make_broken_residency() -> type:
    from pinot_tpu.segment.residency import RESIDENT, ResidencyManager

    class NoUnchargeOnAbortRM(ResidencyManager):
        def abort_stage(self, group: Tuple) -> None:
            with self._lock:
                e = self._entries.get(group)
                if e is None:
                    return
                e.pending = 0
                if e.nbytes > 0:
                    e.state = RESIDENT
                else:
                    del self._entries[group]
                e.event.set()
            # MUTATION: the pending bytes are never uncharged — a mid-stage
            # crash leaks its charge forever

    return NoUnchargeOnAbortRM


# ---------------------------------------------------------------------------
# admission: reserve_or_wait never overcommits; waiters woken or timed out
# ---------------------------------------------------------------------------
class AdmissionModel(BaseModel):
    name = "admission"
    MUTATIONS = ("if_not_while", "notify_one")

    BUDGET = 100

    def setup(self) -> None:
        from pinot_tpu.cluster.admission import ResourceBudget

        cls = ResourceBudget
        if self.mutation == "if_not_while":
            cls = _make_if_not_while()
        elif self.mutation == "notify_one":
            cls = _make_notify_one()
        self.budget = cls(self.BUDGET)
        self.budget.clock = threads.monotonic  # fake clock under the checker
        self.served = 0
        self.both_held = threads.Event()
        self.held = 0

    def _whole(self) -> None:
        t = self.budget.reserve_or_wait(100, what="mc-big", max_wait_ms=10_000)
        try:
            threads.checkpoint()
        finally:
            self.budget.release(t)
        self.served += 1

    def _half(self) -> None:
        t = self.budget.reserve_or_wait(50, what="mc-half", max_wait_ms=10_000)
        try:
            self.held += 1
            if self.held >= 2:
                self.both_held.set()
            # hold until BOTH halves are in: a lost wakeup cannot hide behind
            # an early release re-notifying the queue
            if not self.both_held.wait(timeout=10_000):
                raise RuntimeError("peer half never reserved (lost wakeup upstream)")
        finally:
            self.budget.release(t)
        self.served += 1

    def threads(self) -> List[Tuple[str, Callable[[], None]]]:
        return [
            ("whole-100", self._whole),
            ("half-50-a", self._half),
            ("half-50-b", self._half),
        ]

    def invariants(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def never_overcommit() -> Optional[str]:
            if self.budget._in_use > self.budget.budget_bytes:
                return (
                    f"reservations overcommitted: {self.budget._in_use} of "
                    f"{self.budget.budget_bytes} bytes"
                )
            return None

        return [("never-overcommits", never_overcommit)]

    def at_quiescence(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def all_served() -> Optional[str]:
            if self.served != 3:
                return f"only {self.served}/3 reservations served (waiter starved)"
            if self.budget._in_use != 0:
                return f"{self.budget._in_use} bytes still reserved at rest"
            return None

        return [("every-waiter-served", all_served)]


def _make_if_not_while() -> type:
    from pinot_tpu.cluster.admission import ResourceBudget

    class IfNotWhileBudget(ResourceBudget):
        def reserve_or_wait(self, nbytes, what="query", query_id=None,
                            deadline=None, max_wait_ms=None, queue_limit=8):
            n = max(0, int(nbytes))
            wait_s = (250.0 if max_wait_ms is None else float(max_wait_ms)) / 1000.0
            with self._lock:
                if self._in_use + n <= self.budget_bytes:
                    return self._reserve_locked(n)
                self._waiters += 1
                try:
                    self._lock.wait(timeout=wait_s)
                finally:
                    self._waiters -= 1
                # MUTATION: `if` where `while` is required — one wake, no
                # re-check of the predicate before charging
                return self._reserve_locked(n)

    return IfNotWhileBudget


def _make_notify_one() -> type:
    from pinot_tpu.cluster.admission import ResourceBudget

    class NotifyOneBudget(ResourceBudget):
        def release(self, ticket: int) -> int:
            with self._lock:
                n = self._by_ticket.pop(ticket, 0)
                self._in_use -= n
                self._publish_locked()
                # MUTATION: notify(1) where notify_all is required — a woken
                # waiter that still does not fit consumes the only wakeup
                self._lock.notify(1)
                return n

    return NotifyOneBudget


# ---------------------------------------------------------------------------
# batcher: no lost and no double-settled futures
# ---------------------------------------------------------------------------
class BatcherModel(BaseModel):
    name = "batcher"
    MUTATIONS = ("double_run", "lost_on_crash")

    def setup(self) -> None:
        from pinot_tpu.cluster.batcher import MicroBatcher

        cls = MicroBatcher
        if self.mutation == "double_run":
            cls = _make_double_run()
        elif self.mutation == "lost_on_crash":
            cls = _make_no_safety_net()
        # runner crashes mid-group only in the crash scenario; the intact
        # batcher's safety net turns that into failed futures (handled
        # below), the mutated twin silently loses the rest of the group
        self.crashy = self.mutation == "lost_on_crash"
        self.b = cls(self._runner, wait_ms=50.0, max_batch=2, clock=threads.monotonic)
        self.futures: List[Any] = []
        self.results: Dict[int, Any] = {}
        self.submitted = 0
        self.all_submitted = threads.Event()

    def _runner(self, entries: List[Any]) -> None:
        for i, e in enumerate(entries):
            if self.crashy and len(entries) >= 2 and i == 1:
                raise RuntimeError("runner crash mid-group")
            e.future.set_result(e.payload * 2)

    def _submit(self, idx: int, payload: int) -> None:
        f = self.b.submit("k", payload)
        self.futures.append(f)
        self.submitted += 1
        if self.submitted >= 2:
            self.all_submitted.set()
        try:
            self.results[idx] = f.result(timeout=10_000)
        except RuntimeError as e:
            # the safety net failing a crashed group's futures is correct
            # protocol behavior — record and move on
            self.results[idx] = e

    def _pump(self) -> None:
        if not self.all_submitted.wait(timeout=10_000):
            raise RuntimeError("submitters never arrived")
        for _ in range(3):
            threads.checkpoint()
            self.b.pump(now=threads.monotonic() + 1.0)
        self.b.flush()

    def threads(self) -> List[Tuple[str, Callable[[], None]]]:
        return [
            ("submit-1", lambda: self._submit(1, 10)),
            ("submit-2", lambda: self._submit(2, 20)),
            ("pumper", self._pump),
        ]

    def invariants(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def settle_once() -> Optional[str]:
            for f in self.futures:
                attempts = getattr(f, "resolve_attempts", 0)
                if attempts > 1:
                    return f"future settled {attempts} times (double-run group)"
            return None

        return [("futures-settle-at-most-once", settle_once)]

    def at_quiescence(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def all_settled() -> Optional[str]:
            pending = sum(len(g.entries) for g in self.b._groups.values())
            if pending:
                return f"{pending} submissions never flushed"
            if set(self.results) != {1, 2}:
                return f"results missing for {sorted({1, 2} - set(self.results))}"
            for idx, payload in ((1, 10), (2, 20)):
                got = self.results[idx]
                if not isinstance(got, RuntimeError) and got != payload * 2:
                    return f"submit-{idx} got {got!r}, wanted {payload * 2}"
            return None

        return [("no-lost-futures", all_settled)]


def _make_double_run() -> type:
    from pinot_tpu.cluster.batcher import MicroBatcher, _Group

    class DoubleRunBatcher(MicroBatcher):
        def submit(self, key, payload):
            from pinot_tpu.cluster.batcher import BatchEntry

            entry = BatchEntry(payload)
            if self.wait_ms <= 0 or self.max_batch <= 1:
                self._run([entry])
                return entry.future
            full = None
            with self._cv:
                group = self._groups.get(key)
                if group is None:
                    group = _Group(self.clock() + self.wait_ms / 1000.0)
                    self._groups[key] = group
                group.entries.append(entry)
                if len(group.entries) >= self.max_batch:
                    # MUTATION: the full group is run inline but NOT removed
                    # from the pending map — the next pump runs it again
                    full = group.entries
                else:
                    self._cv.notify_all()
            if full is not None:
                self._run(full)
            return entry.future

    return DoubleRunBatcher


def _make_no_safety_net() -> type:
    from pinot_tpu.cluster.batcher import MicroBatcher

    class NoSafetyNetBatcher(MicroBatcher):
        def _run(self, entries) -> None:
            # MUTATION: no safety net — a runner crash mid-group leaves the
            # unreached entries' futures unresolved forever
            self.runner(entries)

    return NoSafetyNetBatcher


# ---------------------------------------------------------------------------
# lease fencing: at most one epoch appends; deposed writer always fenced
# ---------------------------------------------------------------------------
class LeaseModel(BaseModel):
    name = "lease"
    MUTATIONS = ("skip_fence",)

    def setup(self) -> None:
        from pinot_tpu.cluster.election import LeaseManager

        self.tmpdir = tempfile.mkdtemp(prefix="mc-lease-")
        self.node_a = LeaseManager(self.tmpdir, "A", ttl_s=60.0, clock=threads.monotonic)
        self.node_b = LeaseManager(self.tmpdir, "B", ttl_s=60.0, clock=threads.monotonic)
        self.journal_lock = threads.Lock()
        self.journal: List[int] = []  # the epoch stamped on each entry  # pinot-lint: disable=W010
        self.fenced: List[str] = []

    def teardown(self) -> None:
        shutil.rmtree(self.tmpdir, ignore_errors=True)

    def _append(self, lm: Any) -> None:
        """MetaJournal.append in miniature: fence-then-write under the
        journal lock, with the write window made visible to the scheduler."""
        with self.journal_lock:
            if self.mutation == "skip_fence":
                # MUTATION: the epoch fence never runs — a deposed writer's
                # stale append lands after the usurper's entries
                threads.checkpoint()
                self.journal.append(lm.epoch)
            else:
                epoch = lm.validate_writer()
                threads.checkpoint()
                self.journal.append(epoch)

    def _writer(self, lm: Any, node: str, appends: int, force: bool) -> None:
        from pinot_tpu.cluster.election import NotLeaderError

        if not lm.try_acquire(force=force):
            return
        for _ in range(appends):
            threads.checkpoint()
            try:
                self._append(lm)
            except NotLeaderError:
                self.fenced.append(node)  # deposed: exactly the fence working
                return

    def threads(self) -> List[Tuple[str, Callable[[], None]]]:
        return [
            ("writer-A", lambda: self._writer(self.node_a, "A", 3, force=False)),
            ("usurper-B", lambda: self._writer(self.node_b, "B", 2, force=True)),
        ]

    def invariants(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def epochs_non_decreasing() -> Optional[str]:
            for i in range(1, len(self.journal)):  # pinot-lint: disable=W010
                if self.journal[i] < self.journal[i - 1]:
                    return (
                        f"journal epochs interleaved: {self.journal} — a deposed "
                        "writer appended after the usurper"
                    )
            return None

        return [("one-epoch-appends", epochs_non_decreasing)]

    def at_quiescence(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def fence_observed() -> Optional[str]:
            for i in range(1, len(self.journal)):  # pinot-lint: disable=W010
                if self.journal[i] < self.journal[i - 1]:
                    return f"journal epochs interleaved at rest: {self.journal}"
            return None

        return [("journal-fenced-at-rest", fence_observed)]


# ---------------------------------------------------------------------------
# knobs: a controller tick publishes atomically; queries never see a mix
# ---------------------------------------------------------------------------
class KnobModel(BaseModel):
    name = "knobs"
    MUTATIONS = ("torn_knob_write",)

    # one controller "tick" always writes these two knobs to the SAME value
    # (both clamp ranges admit it), so any reader observing them unequal —
    # other than the env-default initial pair — caught a mid-tick mix
    PAIR = ("batch_wait_ms", "hedge_budget_pct")
    TICKS = (3.0, 5.0, 7.0)

    def setup(self) -> None:
        from pinot_tpu.cluster.autopilot import KnobRegistry

        cls = KnobRegistry
        if self.mutation == "torn_knob_write":
            cls = _make_torn_registry()
        self.reg = cls()
        a, b = self.PAIR
        # lock-free spec reads: setup runs on the harness thread, where the
        # deterministic provider's lock may not be acquired
        self.initial = (self.reg.initial(a), self.reg.initial(b))
        self.torn: List[str] = []

    def _controller(self) -> None:
        a, b = self.PAIR
        for v in self.TICKS:
            threads.checkpoint()
            self.reg.set_many({a: v, b: v}, who="mc-tick")

    def _query(self) -> None:
        a, b = self.PAIR
        for _ in range(4):
            threads.checkpoint()
            view = self.reg.view()
            got = (view[a], view[b])
            if got != self.initial and got[0] != got[1]:
                self.torn.append(f"{a}={got[0]} with {b}={got[1]}")

    def threads(self) -> List[Tuple[str, Callable[[], None]]]:
        return [
            ("controller", self._controller),
            ("query-1", self._query),
            ("query-2", self._query),
        ]

    def invariants(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def coherent_snapshot() -> Optional[str]:
            if self.torn:
                return f"query observed a mid-tick knob mix: {self.torn[0]}"
            return None

        return [("coherent-knob-snapshot", coherent_snapshot)]

    def at_quiescence(self) -> List[Tuple[str, Callable[[], Optional[str]]]]:
        def final_tick_applied() -> Optional[str]:
            a, b = self.PAIR
            last = self.TICKS[-1]
            # raw read: quiescence callbacks run on the harness thread with
            # every model thread parked  # pinot-lint: disable=W010
            ov = self.reg._overrides
            if (ov.get(a), ov.get(b)) != (last, last):
                return (
                    f"final tick lost: {a}={ov.get(a)} {b}={ov.get(b)}, "
                    f"wanted both {last}"
                )
            return None

        return [("last-tick-fully-applied", final_tick_applied)]


def _make_torn_registry() -> type:
    from pinot_tpu.cluster.autopilot import KnobRegistry

    class TornKnobRegistry(KnobRegistry):
        def set_many(self, updates, who="manual"):
            # MUTATION: knobs land one swap at a time with a visible window
            # between them — a concurrent view() reads half the tick
            out = {}
            for n, v in updates.items():
                out.update(super().set_many({n: v}, who=who))
                threads.checkpoint()
            return out

    return TornKnobRegistry


PROTOCOLS: Dict[str, type] = {
    ResidencyModel.name: ResidencyModel,
    AdmissionModel.name: AdmissionModel,
    BatcherModel.name: BatcherModel,
    LeaseModel.name: LeaseModel,
    KnobModel.name: KnobModel,
}
