"""Host-device sync auditor (W013/W014) over a Project.

Propagates "device value" taint from `jnp.*` / `lax.*` /
`jax.device_put` / jitted-callable sources through local dataflow and
the call graph (a function whose return value is tainted marks every
call site tainted — computed as a fixpoint over the whole package), then
flags the two ways a device value silently stalls the async dispatch
pipeline *on the warm query path*:

  W013  implicit device->host sync: float()/int()/bool()/.item()/
        .tolist()/np.asarray() on a device value, or any
        block_until_ready (the warm path gets exactly one sanctioned
        fence — the r8 `device_wait` in ServerInstance.execute, carried
        on the allowlist below).
  W014  host control flow (if/while) branching on a device value —
        forces a blocking transfer at trace boundaries; the decision
        belongs at plan time or inside the graph (jnp.where/lax.cond).

Warm path = parallel/engine.py, query/reduce.py, cluster/server.py,
ops/* (the modules between "plan hit" and "rows returned").  Function
bodies that are themselves traced (passed to jit/pallas_call/shard_map/
vmap/fori_loop/...) are excluded — inside a trace these ops are either
fine or a trace error, not a silent sync.  Taint does not flow through
parameters (only through returns); that keeps the pass fast and
false-positive-shy at the cost of missing device values handed down as
arguments — the per-file W002 covers the jitted side of that gap.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pinot_tpu.analysis.engine import FunctionInfo, Pass, Project
from pinot_tpu.analysis.repo_lint import Finding

WARM_PATH_SUFFIXES = (
    "parallel/engine.py",
    "query/reduce.py",
    "cluster/server.py",
)
WARM_PATH_DIRS = ("/ops/",)

# the sanctioned warm-path fences (r8 device_wait): one block_until_ready
# over all pending outputs, splitting device time from host dispatch in the
# trace tree — execute_batch carries the identical fence for the vmapped
# cross-query launches (trace-enabled only)
ALLOWED_SYNCS: Set[Tuple[str, str]] = {
    ("cluster/server.py", "ServerInstance.execute"),
    ("cluster/server.py", "ServerInstance.execute_batch"),
}

_DEVICE_PREFIXES = ("jax.numpy.", "jax.lax.")
_DEVICE_CALLS = {"jax.device_put", "jax.block_until_ready", "jax.eval_shape"}
_SANITIZERS = {"jax.device_get"}
# jnp functions whose RESULT lives on host (dtype/shape metadata predicates)
_HOST_RESULT_JAX = {
    "jax.numpy.issubdtype",
    "jax.numpy.isdtype",
    "jax.numpy.result_type",
    "jax.numpy.promote_types",
    "jax.numpy.can_cast",
    "jax.numpy.dtype",
    "jax.numpy.shape",
    "jax.numpy.ndim",
    "jax.numpy.iinfo",
    "jax.numpy.finfo",
    "jax.default_backend",
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
}
_METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "sharding", "weak_type", "at"}
_HOST_RESULT_METHODS = {"item", "tolist"}  # sinks; their result is host

_TRACE_WRAPPERS = (
    "jit", "pallas_call", "shard_map", "vmap", "pmap", "fori_loop",
    "while_loop", "scan", "cond", "checkpoint", "custom_vjp", "custom_jvp",
    "named_call", "grad",
)


def _callable_name(func: ast.AST) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _is_trace_wrapper(func: ast.AST) -> bool:
    name = _callable_name(func)
    return any(w in name for w in _TRACE_WRAPPERS)


def traced_names(tree: ast.Module) -> Set[str]:
    """Function names whose bodies execute under a JAX trace: decorated
    with @*jit*, or passed by name to jit/pallas_call/shard_map/vmap/
    fori_loop/... anywhere in the module."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_trace_wrapper(node.func):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dec.func if isinstance(dec, ast.Call) else dec
                if _is_trace_wrapper(d) or any(
                    _is_trace_wrapper(a)
                    for a in (dec.args if isinstance(dec, ast.Call) else [])
                ):
                    names.add(node.name)
    return names


class _Scope:
    """Flow-sensitive local taint for one function body."""

    def __init__(
        self,
        pass_: "DeviceSyncPass",
        fi: FunctionInfo,
        project: Project,
        returns_device: Set[str],
        module_traced: Set[str],
        findings: Optional[List[Finding]],
    ) -> None:
        self.p = pass_
        self.fi = fi
        self.project = project
        self.returns_device = returns_device
        self.module_traced = module_traced
        self.findings = findings
        self.taint: Set[str] = set()
        self.jitted_locals: Set[str] = set()
        self.returns_tainted = False
        self._reported: Set[Tuple[int, str]] = set()

    # -- expression taint --------------------------------------------------

    def tainted(self, e: Optional[ast.AST]) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Name):
            return e.id in self.taint
        if isinstance(e, ast.Call):
            return self._call_tainted(e)
        if isinstance(e, ast.Attribute):
            return self.tainted(e.value) and e.attr not in _METADATA_ATTRS
        if isinstance(e, ast.Subscript):
            return self.tainted(e.value)
        if isinstance(e, ast.Compare):
            # `is`/`is not` never touch values; `in`/`not in` against a host
            # container of device values (the params-dict idiom) is a host
            # key lookup, not a sync
            _HOST_OPS = (ast.Is, ast.IsNot, ast.In, ast.NotIn)
            t = False
            if not isinstance(e.ops[0], _HOST_OPS):
                t = self.tainted(e.left)
            for op, comp in zip(e.ops, e.comparators):
                if not isinstance(op, _HOST_OPS):
                    t = t or self.tainted(comp)
            return t
        if isinstance(e, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        # generic containers/operators: tainted if any child expression is
        return any(
            self.tainted(c)
            for c in ast.iter_child_nodes(e)
            if isinstance(c, ast.expr)
        )

    def _call_tainted(self, e: ast.Call) -> bool:
        target = self.project.resolve_expr(self.fi, e.func)
        if target is not None:
            if target in _SANITIZERS or target in _HOST_RESULT_JAX:
                return False
            if target in _DEVICE_CALLS or target.startswith(_DEVICE_PREFIXES):
                return True
            if target.startswith("jax.tree_util.") or target.startswith("jax.tree."):
                return any(self.tainted(a) for a in e.args)
            if target in self.returns_device:
                return True
            if target.startswith("numpy."):
                return False  # host result (and possibly a sink — checked there)
        if isinstance(e.func, ast.Name) and e.func.id in self.jitted_locals:
            return True
        if isinstance(e.func, ast.Attribute):
            if e.func.attr in _HOST_RESULT_METHODS:
                return False
            # method call on a device value stays on device (x.sum(), x.astype())
            return self.tainted(e.func.value)
        return False

    # -- sinks -------------------------------------------------------------

    def _warm(self) -> bool:
        rel = self.fi.module.relpath
        return rel.endswith(self.p.warm_suffixes) or any(
            d in f"/{rel}" for d in self.p.warm_dirs
        )

    def _allowed(self) -> bool:
        sym = self._symbol()
        rel = self.fi.module.relpath
        return any(rel.endswith(p) and sym == s for p, s in self.p.allowed_syncs)

    def _symbol(self) -> str:
        if self.fi.cls is not None:
            return f"{self.fi.cls.name}.{self.fi.name}"
        return self.fi.name

    def _emit(self, line: int, rule: str, msg: str, hint: str) -> None:
        if self.findings is None:
            return
        key = (line, rule)
        if key in self._reported:
            return
        self._reported.add(key)
        self.findings.append(
            Finding(self.fi.module.relpath, line, rule, msg, hint=hint, symbol=self._symbol())
        )

    def check_call_sink(self, e: ast.Call) -> None:
        if self.findings is None or not self._warm():
            return
        target = self.project.resolve_expr(self.fi, e.func)
        name = _callable_name(e.func)
        if name == "block_until_ready" or target == "jax.block_until_ready":
            if not self._allowed():
                self._emit(
                    e.lineno,
                    "W013",
                    "block_until_ready on the warm path — every call is a "
                    "full pipeline stall",
                    "drain via jax.device_get at the collect point; the warm "
                    "path's one sanctioned fence is ServerInstance.execute's "
                    "device_wait",
                )
            return
        if (
            isinstance(e.func, ast.Name)
            and e.func.id in ("float", "int", "bool")
            and any(self.tainted(a) for a in e.args)
        ):
            self._emit(
                e.lineno,
                "W013",
                f"{e.func.id}() on a device value forces an implicit "
                "device->host sync",
                "materialize once via jax.device_get() at the drain point, "
                "then convert on host",
            )
            return
        if (
            isinstance(e.func, ast.Attribute)
            and e.func.attr in _HOST_RESULT_METHODS
            and self.tainted(e.func.value)
        ):
            self._emit(
                e.lineno,
                "W013",
                f".{e.func.attr}() on a device value forces an implicit "
                "device->host sync",
                "materialize once via jax.device_get() at the drain point",
            )
            return
        if target is not None and target.startswith("numpy.") and any(
            self.tainted(a) for a in e.args
        ):
            self._emit(
                e.lineno,
                "W013",
                f"{target}() on a device value forces an implicit "
                "device->host transfer",
                "keep the computation in jnp on device, or jax.device_get() "
                "once and reuse the host array",
            )

    def check_branch(self, test: ast.AST, lineno: int) -> None:
        if self.findings is None or not self._warm():
            return
        if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        ):
            return
        if self.tainted(test):
            self._emit(
                lineno,
                "W014",
                "host control flow branches on a device value (blocking "
                "transfer at the branch)",
                "hoist the decision to plan time or compute both sides with "
                "jnp.where/lax.cond",
            )

    # -- statement walk ----------------------------------------------------

    def run(self, body: List[ast.stmt]) -> None:
        self.process_block(body)

    def process_block(self, stmts: Iterable[ast.stmt]) -> None:
        for s in stmts:
            self.process_stmt(s)

    def _scan_sinks(self, node: ast.AST) -> None:
        """Check every call in an expression tree, skipping deferred bodies."""
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(cur, ast.Call):
                self.check_call_sink(cur)
            stack.extend(ast.iter_child_nodes(cur))

    def _assign_target(self, target: ast.AST, value_tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                self.taint.add(target.id)
            else:
                self.taint.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_target(elt, value_tainted)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, value_tainted)
        elif isinstance(target, ast.Subscript) and value_tainted:
            # storing a device value into a local container taints the container
            if isinstance(target.value, ast.Name):
                self.taint.add(target.value.id)

    def _note_jitted_local(self, target: ast.AST, value: ast.AST) -> None:
        if not isinstance(target, ast.Name) or not isinstance(value, ast.Call):
            return
        name = _callable_name(value.func)
        if any(w in name for w in ("jit", "shard_map", "pmap")):
            self.jitted_locals.add(target.id)

    def process_stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Assign):
            self._scan_sinks(s.value)
            t = self.tainted(s.value)
            for target in s.targets:
                self._assign_target(target, t)
                self._note_jitted_local(target, s.value)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._scan_sinks(s.value)
                self._assign_target(s.target, self.tainted(s.value))
        elif isinstance(s, ast.AugAssign):
            self._scan_sinks(s.value)
            if self.tainted(s.value):
                self._assign_target(s.target, True)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                self._scan_sinks(s.value)
                if self.tainted(s.value):
                    self.returns_tainted = True
        elif isinstance(s, ast.Expr):
            self._scan_sinks(s.value)
        elif isinstance(s, ast.If):
            self._scan_sinks(s.test)
            self.check_branch(s.test, s.lineno)
            self.process_block(s.body)
            self.process_block(s.orelse)
        elif isinstance(s, ast.While):
            self._scan_sinks(s.test)
            self.check_branch(s.test, s.lineno)
            for _ in range(2):  # second pass picks up loop-carried taint
                self.process_block(s.body)
            self.process_block(s.orelse)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self._scan_sinks(s.iter)
            iter_tainted = self.tainted(s.iter)
            for _ in range(2):
                self._assign_target(s.target, iter_tainted)
                self.process_block(s.body)
            self.process_block(s.orelse)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._scan_sinks(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_target(
                        item.optional_vars, self.tainted(item.context_expr)
                    )
            self.process_block(s.body)
        elif isinstance(s, ast.Try):
            self.process_block(s.body)
            for h in s.handlers:
                self.process_block(h.body)
            self.process_block(s.orelse)
            self.process_block(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if s.name in self.module_traced:
                return  # traced body: device ops there are the point
            inner = _Scope(
                self.p, self.fi, self.project, self.returns_device,
                self.module_traced, self.findings,
            )
            inner.taint = set(self.taint)
            inner.jitted_locals = set(self.jitted_locals)
            inner._reported = self._reported
            inner.process_block(s.body)
        elif isinstance(s, (ast.Assert, ast.Raise)):
            for child in ast.iter_child_nodes(s):
                self._scan_sinks(child)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    self.taint.discard(t.id)


class DeviceSyncPass(Pass):
    name = "device_sync"

    def __init__(
        self,
        warm_suffixes: Optional[Tuple[str, ...]] = None,
        warm_dirs: Optional[Tuple[str, ...]] = None,
        allowed_syncs: Optional[Set[Tuple[str, str]]] = None,
    ) -> None:
        self.warm_suffixes = warm_suffixes or WARM_PATH_SUFFIXES
        self.warm_dirs = warm_dirs or WARM_PATH_DIRS
        self.allowed_syncs = allowed_syncs if allowed_syncs is not None else ALLOWED_SYNCS

    def run(self, project: Project) -> List[Finding]:
        module_traced: Dict[str, Set[str]] = {
            name: traced_names(mi.tree) for name, mi in project.modules.items()
        }

        def analyze(fi: FunctionInfo, returns_device: Set[str], findings):
            scope = _Scope(
                self, fi, project, returns_device,
                module_traced[fi.module.name], findings,
            )
            scope.run(fi.node.body)
            return scope.returns_tainted

        # fixpoint: which project functions return device values
        returns_device: Set[str] = set()
        for _ in range(8):
            changed = False
            for fi in project.functions.values():
                if fi.name in module_traced[fi.module.name]:
                    continue
                if fi.qname in returns_device:
                    continue
                if analyze(fi, returns_device, None):
                    returns_device.add(fi.qname)
                    changed = True
            if not changed:
                break

        findings: List[Finding] = []
        for fi in project.functions.values():
            if fi.name in module_traced[fi.module.name]:
                continue
            analyze(fi, returns_device, findings)
        return findings
