"""Deterministic cooperative scheduler: the dynamic half of the checker.

The serving tier's concurrency protocols are exercised under a scheduler
that owns every interleaving decision.  Model threads are real OS threads,
but they run ONE AT A TIME: each parks on its own semaphore and only the
scheduler's main loop hands out the single run token.  Every primitive
operation (lock acquire/release, condition wait/notify, event wait/set,
future resolve, an explicit `threads.checkpoint()`) is a YIELD POINT where
the token returns to the scheduler, which picks the next runnable thread

  * by a seeded RNG (random-schedule exploration),
  * under a preemption bound (at most K switches away from a runnable
    thread — the CHESS result: most concurrency bugs need few preemptions),
  * or from a FORCED schedule (bit-identical replay of a failing trace).

Because only one model thread ever runs and it can only lose the token at
a yield point, the protocol state visible between steps is a consistent
snapshot: the harness checks invariants after every step without any
locking of its own.

Time is fake: `provider.monotonic()` reads a logical clock that advances
ONLY when every live thread is blocked and at least one of them holds a
timed wait — then the earliest deadline fires (the wait times out).  A
timeout can therefore never preempt progress, and a schedule's outcome is
a pure function of (seed, preemption bound, forced schedule).

Failure modes the scheduler itself detects:

  * DeadlockError — every live thread is blocked and none holds a timed
    wait (includes lost wakeups: a condition waiter nobody can notify);
  * LivelockError — a schedule exceeds `max_steps` without quiescing
    (a spin loop that yields forever).

Primitive semantics mirror the stdlib: non-reentrant Lock, reentrant
RLock, Condition with FIFO waiters (notify wakes in wait order; woken
waiters re-contend for the lock), Event, Thread with join, and a Future
matching `concurrent.futures.Future` closely enough for the batcher
(InvalidStateError on double-resolve, TimeoutError from `result`).
"""
from __future__ import annotations

import random
from concurrent.futures import InvalidStateError, TimeoutError as FutureTimeoutError
from typing import Any, Callable, Dict, List, Optional, Tuple

import threading as _real_threading

NEW = "new"
RUNNABLE = "runnable"
BLOCKED = "blocked"
DONE = "done"


class DeadlockError(AssertionError):
    """All live model threads are blocked with no timed wait to fire."""


class LivelockError(AssertionError):
    """A schedule ran past max_steps without quiescing (spin loop)."""


class TraceDivergenceError(AssertionError):
    """A forced replay schedule named a thread that is not runnable —
    the code under test changed since the trace was captured."""


class _Killed(BaseException):
    """Unwinds a parked model thread during scheduler shutdown.  Derives
    from BaseException so model code's `except Exception` cannot eat it."""


class _Task:
    __slots__ = (
        "tid", "name", "target", "sem", "state", "block_kind", "block_obj",
        "deadline", "timed_out", "exc", "thread", "started",
    )

    def __init__(self, tid: int, name: str, target: Callable[[], None]):
        self.tid = tid
        self.name = name
        self.target = target
        self.sem = _real_threading.Semaphore(0)
        self.state = NEW
        self.block_kind: Optional[str] = None   # "lock"|"cond"|"event"|"join"|"future"|"sleep"
        self.block_obj: Any = None
        self.deadline: Optional[float] = None   # fake-clock deadline for timed waits
        self.timed_out = False
        self.exc: Optional[BaseException] = None
        self.thread: Optional[_real_threading.Thread] = None
        self.started = False

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<task {self.tid}:{self.name} {self.state}>"


class DeterministicScheduler:
    """One explored schedule: spawn tasks, `run()`, inspect `trace`."""

    def __init__(
        self,
        seed: int = 0,
        preemption_bound: Optional[int] = None,
        schedule: Optional[List[int]] = None,
        max_steps: int = 20_000,
    ):
        self.rng = random.Random(seed)
        self.seed = seed
        self.preemption_bound = preemption_bound
        self.preemptions = 0
        self.forced = list(schedule) if schedule is not None else None
        self._forced_pos = 0
        self.max_steps = max_steps
        self.tasks: List[_Task] = []
        self.current: Optional[_Task] = None
        self.trace: List[int] = []
        self.clock_now = 0.0
        self.steps = 0
        self.on_step: Optional[Callable[[], None]] = None
        self._abort = False
        self._main_sem = _real_threading.Semaphore(0)

    # -- task plumbing ----------------------------------------------------

    def create_task(self, target: Callable[[], None], name: str) -> _Task:
        task = _Task(len(self.tasks), name or f"t{len(self.tasks)}", target)
        self.tasks.append(task)
        return task

    def start_task(self, task: _Task) -> None:
        if task.started:
            raise RuntimeError(f"task {task.name} started twice")
        task.started = True
        task.state = RUNNABLE
        task.thread = _real_threading.Thread(
            target=self._task_main, args=(task,), name=f"mc-{task.name}", daemon=True
        )
        task.thread.start()

    def _task_main(self, task: _Task) -> None:
        task.sem.acquire()  # park until first scheduled
        try:
            if not self._abort:
                task.target()
        except _Killed:
            pass
        except BaseException as e:  # noqa: BLE001 — recorded as a model failure
            task.exc = e
        finally:
            task.state = DONE
            self._wake("join", task)
            self._main_sem.release()

    # -- token handoff (called from MODEL threads only) -------------------

    def _switch_out(self) -> None:
        """Give the token back to the main loop and park until rescheduled."""
        task = self.current
        assert task is not None, "primitive used outside a scheduled thread"
        self._main_sem.release()
        task.sem.acquire()
        if self._abort:
            raise _Killed()

    def yield_point(self) -> None:
        """A scheduling point where the thread stays runnable."""
        if self._abort:
            raise _Killed()
        self._switch_out()

    def block(
        self, kind: str, obj: Any, timeout: Optional[float] = None
    ) -> bool:
        """Park the current thread on (kind, obj); returns True when the
        wake was a fake-clock TIMEOUT rather than an explicit wake."""
        if self._abort:
            raise _Killed()
        task = self.current
        assert task is not None
        task.block_kind, task.block_obj = kind, obj
        task.deadline = (
            self.clock_now + timeout if timeout is not None and timeout > 0 else None
        )
        task.timed_out = False
        task.state = BLOCKED
        self._switch_out()
        task.block_kind = task.block_obj = None
        task.deadline = None
        return task.timed_out

    def _wake(self, kind: str, obj: Any, limit: Optional[int] = None) -> int:
        """Mark threads blocked on (kind, obj) runnable, FIFO by tid order
        of blocking; returns how many woke."""
        n = 0
        for t in self.tasks:
            if t.state == BLOCKED and t.block_kind == kind and t.block_obj is obj:
                t.state = RUNNABLE
                n += 1
                if limit is not None and n >= limit:
                    break
        return n

    # -- main loop (called from the HARNESS thread) -----------------------

    def _choose(self, runnable: List[_Task]) -> _Task:
        if self.forced is not None:
            if self._forced_pos >= len(self.forced):
                raise TraceDivergenceError(
                    f"forced schedule exhausted at step {self.steps} with "
                    f"{len(runnable)} thread(s) still live"
                )
            tid = self.forced[self._forced_pos]
            self._forced_pos += 1
            for t in runnable:
                if t.tid == tid:
                    return t
            raise TraceDivergenceError(
                f"forced schedule chose t{tid} at step {self.steps} but runnable "
                f"set is {[t.tid for t in runnable]}"
            )
        cur = self.current
        cur_runnable = cur is not None and cur.state == RUNNABLE and cur in runnable
        if (
            self.preemption_bound is not None
            and cur_runnable
            and self.preemptions >= self.preemption_bound
        ):
            return cur  # budget spent: run the current thread to its next block
        pick = self.rng.choice(sorted(runnable, key=lambda t: t.tid))
        if cur_runnable and pick is not cur:
            self.preemptions += 1
        return pick

    def _fire_earliest_timeout(self) -> bool:
        timed = [t for t in self.tasks if t.state == BLOCKED and t.deadline is not None]
        if not timed:
            return False
        deadline = min(t.deadline for t in timed)
        self.clock_now = max(self.clock_now, deadline)
        for t in timed:
            if t.deadline is not None and t.deadline <= self.clock_now:
                t.timed_out = True
                t.state = RUNNABLE
        return True

    def blocked_report(self) -> List[str]:
        out = []
        for t in self.tasks:
            if t.state == BLOCKED:
                obj = t.block_obj
                desc = getattr(obj, "mc_name", None) or type(obj).__name__
                out.append(f"{t.name} waits on {t.block_kind}:{desc}")
        return out

    def run(self) -> None:
        """Drive to quiescence (all tasks DONE) or raise Deadlock/Livelock.
        `on_step` runs after every step — invariant checks live there."""
        while True:
            live = [t for t in self.tasks if t.started and t.state != DONE]
            if not live:
                return
            runnable = [t for t in live if t.state == RUNNABLE]
            if not runnable:
                if self._fire_earliest_timeout():
                    continue
                raise DeadlockError(
                    "deadlock: all live threads blocked — " + "; ".join(self.blocked_report())
                )
            self.steps += 1
            if self.steps > self.max_steps:
                raise LivelockError(
                    f"schedule exceeded {self.max_steps} steps without quiescing"
                )
            chosen = self._choose(runnable)
            self.trace.append(chosen.tid)
            self.current = chosen
            chosen.sem.release()
            self._main_sem.acquire()
            if self.on_step is not None:
                self.on_step()

    def shutdown(self) -> None:
        """Kill parked threads after a failure: every parked semaphore is
        released with `_abort` set, so each thread raises `_Killed` at its
        park point and unwinds; primitives short-circuit during abort so
        `finally:` blocks in model code cannot re-park."""
        self._abort = True
        for t in self.tasks:
            if t.started and t.state != DONE:
                for _ in range(4):
                    t.sem.release()
        for t in self.tasks:
            if t.thread is not None:
                t.thread.join(timeout=2.0)

    # -- clock ------------------------------------------------------------

    def monotonic(self) -> float:
        return self.clock_now


# ---------------------------------------------------------------------------
# primitives (the scheduler-backed utils.threads provider)
# ---------------------------------------------------------------------------
class SchedLock:
    """Non-reentrant lock.  State changes happen atomically between yield
    points (only one model thread runs at a time), so no real lock backs
    the bookkeeping."""

    def __init__(self, sched: DeterministicScheduler, name: str = "lock"):
        self._sched = sched
        self.mc_name = name
        self.owner: Optional[_Task] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = self._sched
        if sched._abort:
            return True
        sched.yield_point()  # interleaving point before the acquisition race
        while self.owner is not None:
            if self.owner is sched.current:
                raise RuntimeError(f"non-reentrant {self.mc_name} re-acquired (self-deadlock)")
            if not blocking:
                return False
            timed_out = sched.block("lock", self, timeout if timeout and timeout > 0 else None)
            if timed_out:
                return False
        self.owner = sched.current
        return True

    def release(self) -> None:
        sched = self._sched
        if sched._abort:
            self.owner = None
            return
        if self.owner is not sched.current:
            raise RuntimeError(f"release of {self.mc_name} not held by releaser")
        self.owner = None
        sched._wake("lock", self)  # all waiters re-contend, stdlib-style
        sched.yield_point()

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self) -> "SchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class SchedRLock:
    def __init__(self, sched: DeterministicScheduler, name: str = "rlock"):
        self._sched = sched
        self.mc_name = name
        self.owner: Optional[_Task] = None
        self.count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = self._sched
        if sched._abort:
            return True
        if self.owner is sched.current:
            self.count += 1
            return True
        sched.yield_point()
        while self.owner is not None and self.owner is not sched.current:
            if not blocking:
                return False
            timed_out = sched.block("lock", self, timeout if timeout and timeout > 0 else None)
            if timed_out:
                return False
        self.owner = sched.current
        self.count += 1
        return True

    def release(self) -> None:
        sched = self._sched
        if sched._abort:
            self.owner, self.count = None, 0
            return
        if self.owner is not sched.current:
            raise RuntimeError(f"release of {self.mc_name} not held by releaser")
        self.count -= 1
        if self.count == 0:
            self.owner = None
            sched._wake("lock", self)
            sched.yield_point()

    def __enter__(self) -> "SchedRLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    # internal: full release/restore for Condition.wait on an RLock
    def _release_save(self) -> int:
        saved, self.count, self.owner = self.count, 0, None
        self._sched._wake("lock", self)
        return saved

    def _acquire_restore(self, saved: int) -> None:
        self.acquire()
        self.count = saved


class SchedCondition:
    """Condition variable over a Sched lock.  Waiters queue FIFO; notify
    moves them to runnable (they re-contend for the lock on wake, exactly
    like the stdlib)."""

    def __init__(self, sched: DeterministicScheduler, lock: Any = None, name: str = "cond"):
        self._sched = sched
        self.mc_name = name
        self._lock = lock if lock is not None else SchedRLock(sched, name=f"{name}.lock")
        self._waiters: List[_Task] = []
        self.notifies_delivered = 0  # observability for W024-style dynamic checks

    # lock interface delegation
    def acquire(self, *a: Any, **kw: Any) -> bool:
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "SchedCondition":
        self._lock.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()

    def _is_owned(self) -> bool:
        return self._lock.owner is self._sched.current

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        if sched._abort:
            return False
        if not self._is_owned():
            raise RuntimeError("cannot wait on un-acquired condition")
        task = sched.current
        assert task is not None
        self._waiters.append(task)
        if isinstance(self._lock, SchedRLock):
            saved = self._lock._release_save()
        else:
            self._lock.release()
            saved = 1
        timed_out = sched.block("cond", self, timeout)
        if task in self._waiters:  # timeout path: notify never removed us
            self._waiters.remove(task)
        if isinstance(self._lock, SchedRLock):
            self._lock._acquire_restore(saved)
        else:
            self._lock.acquire()
        return not timed_out

    def notify(self, n: int = 1) -> None:
        sched = self._sched
        if sched._abort:
            return
        if not self._is_owned():
            raise RuntimeError("cannot notify on un-acquired condition")
        for task in self._waiters[:n]:
            self._waiters.remove(task)
            if task.state == BLOCKED and task.block_kind == "cond" and task.block_obj is self:
                task.state = RUNNABLE
            self.notifies_delivered += 1

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


class SchedEvent:
    def __init__(self, sched: DeterministicScheduler, name: str = "event"):
        self._sched = sched
        self.mc_name = name
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        sched = self._sched
        self._flag = True
        if sched._abort:
            return
        sched._wake("event", self)
        sched.yield_point()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        if sched._abort:
            return self._flag
        sched.yield_point()
        while not self._flag:
            timed_out = sched.block("event", self, timeout)
            if timed_out:
                break
        return self._flag


class SchedThread:
    """threading.Thread lookalike registered with the scheduler."""

    def __init__(
        self,
        group: None = None,
        target: Optional[Callable] = None,
        name: Optional[str] = None,
        args: Tuple = (),
        kwargs: Optional[Dict] = None,
        daemon: Optional[bool] = None,
    ):
        sched = _ambient_scheduler()
        self._sched = sched
        self.daemon = daemon
        kwargs = kwargs or {}

        def _run() -> None:
            if target is not None:
                target(*args, **kwargs)

        self._task = sched.create_task(_run, name or f"thread-{len(sched.tasks)}")
        self.name = self._task.name

    def start(self) -> None:
        self._sched.start_task(self._task)

    def is_alive(self) -> bool:
        return self._task.started and self._task.state != DONE

    def join(self, timeout: Optional[float] = None) -> None:
        sched = self._sched
        if sched._abort:
            return
        while self._task.state != DONE:
            timed_out = sched.block("join", self._task, timeout)
            if timed_out:
                return


class SchedFuture:
    """concurrent.futures.Future lookalike: InvalidStateError on double
    resolution, TimeoutError from result(), waiters parked on the
    scheduler.  `resolve_attempts` counts resolution calls (including
    rejected doubles) for the model invariants."""

    def __init__(self, sched: DeterministicScheduler, name: str = "future"):
        self._sched = sched
        self.mc_name = name
        self._done = False
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self.resolve_attempts = 0

    def done(self) -> bool:
        return self._done

    def set_result(self, value: Any) -> None:
        self.resolve_attempts += 1
        if self._done:
            raise InvalidStateError(f"{self.mc_name} already resolved")
        self._done = True
        self._result = value
        sched = self._sched
        if not sched._abort:
            sched._wake("future", self)
            sched.yield_point()

    def set_exception(self, exc: BaseException) -> None:
        self.resolve_attempts += 1
        if self._done:
            raise InvalidStateError(f"{self.mc_name} already resolved")
        self._done = True
        self._exc = exc
        sched = self._sched
        if not sched._abort:
            sched._wake("future", self)
            sched.yield_point()

    def exception(self, timeout: Optional[float] = None) -> Optional[BaseException]:
        self._wait_done(timeout)
        return self._exc

    def result(self, timeout: Optional[float] = None) -> Any:
        self._wait_done(timeout)
        if self._exc is not None:
            raise self._exc
        return self._result

    def _wait_done(self, timeout: Optional[float]) -> None:
        sched = self._sched
        if sched._abort:
            return
        sched.yield_point()
        while not self._done:
            timed_out = sched.block("future", self, timeout)
            if timed_out and not self._done:
                raise FutureTimeoutError(f"{self.mc_name} unresolved past timeout")


# ---------------------------------------------------------------------------
# the provider
# ---------------------------------------------------------------------------
_AMBIENT: Optional["SchedulerProvider"] = None


def _ambient_scheduler() -> DeterministicScheduler:
    if _AMBIENT is None:
        raise RuntimeError("SchedThread constructed with no scheduler provider installed")
    return _AMBIENT.sched


class SchedulerProvider:
    """The utils.threads provider backed by one DeterministicScheduler.
    Install with `threads.use_provider(provider)` for the duration of a
    schedule; `Thread` needs the ambient hookup because the stdlib Thread
    signature has no room for the scheduler handle."""

    name = "model-check"

    def __init__(self, sched: DeterministicScheduler):
        self.sched = sched
        self._n = 0

    def _name(self, kind: str) -> str:
        self._n += 1
        return f"{kind}{self._n}"

    def Lock(self) -> SchedLock:
        return SchedLock(self.sched, name=self._name("lock"))

    def RLock(self) -> SchedRLock:
        return SchedRLock(self.sched, name=self._name("rlock"))

    def Condition(self, lock: Any = None) -> SchedCondition:
        return SchedCondition(self.sched, lock=lock, name=self._name("cond"))

    def Event(self) -> SchedEvent:
        return SchedEvent(self.sched, name=self._name("event"))

    def Future(self) -> SchedFuture:
        return SchedFuture(self.sched, name=self._name("future"))

    def Thread(self, *args: Any, **kwargs: Any) -> SchedThread:
        global _AMBIENT
        _AMBIENT = self
        return SchedThread(*args, **kwargs)

    def monotonic(self) -> float:
        return self.sched.monotonic()

    def checkpoint(self) -> None:
        if not self.sched._abort:
            self.sched.yield_point()

    def __enter__(self) -> "SchedulerProvider":
        global _AMBIENT
        _AMBIENT = self
        return self

    def __exit__(self, *exc: Any) -> None:
        global _AMBIENT
        _AMBIENT = None
