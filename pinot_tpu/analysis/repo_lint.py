"""JAX-aware repo lint: ast pass over the pinot_tpu tree.

Per-file rules, each targeting an anti-pattern this codebase has actually
been bitten by (ADVICE r5) or that silently degrades TPU throughput:

  W001 float-literal-in-jit   bare float literal used in arithmetic or a
                              comparison INSIDE a jitted kernel body —
                              python floats are weak-typed and promote
                              int columns to f32 mid-kernel.
  W002 host-sync-in-jit       .item() / np.asarray / .block_until_ready /
                              jax.device_get inside a jitted kernel body:
                              a host<->device sync point inside traced
                              code either fails to trace or serializes
                              the async dispatch pipeline.
  W003 jit-in-loop            jax.jit(...) constructed inside a for/while
                              body, or jit-then-call in one expression
                              (jax.jit(f)(x)): a fresh wrapper per
                              iteration/call defeats the compile cache.
  W004 unlocked-shared-rmw    read-modify-write of a shared `self.*`
                              attribute in a cluster/ class method with no
                              enclosing `with <lock>:` — the exact broker
                              token-bucket race class from ADVICE r5.
  W005 wall-clock-latency     time.time() used in elapsed-time math (a
                              subtraction/comparison, directly or through a
                              local alias) — deadlines, heartbeat staleness
                              and latency measures must ride the monotonic
                              clock or an NTP step mis-expires them.  Epoch
                              *timestamps* (creationTimeMs etc.) are fine.
  W006 swallowed-exception    an `except` handler in cluster/ whose body
                              neither re-raises nor makes ANY call (no
                              metrics/log/record) — faults on the serving
                              path must be observable, never dropped.
  W007 unbounded-metric-name  a metric/span name (first argument of a
                              .counter/.gauge/.timer/.histogram/.span call)
                              built from an f-string interpolating an
                              unbounded value (sql text, query/request ids,
                              uuids, fingerprints): every distinct value
                              mints a new time series — a cardinality
                              explosion in the registry and any scraper.
                              Bounded label spaces (table, segment, server
                              names) interpolate freely.
  W008 literal-in-plan-key    a full `.fingerprint()` (which bakes predicate
                              literals) used as a *plan-cache* key — every
                              distinct literal recompiles the same kernel
                              shape.  Plan caches must key on
                              `.shape_fingerprint()` (query/shape.py), which
                              canonicalizes literals into parameter slots.
                              Result caches and logs keep the full form.
  W015 unbounded-growth       a container attribute created unbounded in
                              `__init__` (list/set/dict/deque-without-maxlen)
                              that a cluster/ *serving-path* method (execute,
                              handle, scatter, admit, record, ...) appends to
                              or keys by a per-request value (query id, sql,
                              uuid), with no eviction anywhere in the class —
                              every request leaks a little host memory until
                              the server OOMs under sustained load.  Any
                              eviction evidence (pop/clear/del/reassignment
                              outside __init__) or a deque(maxlen=...) bound
                              exempts the attribute; dict writes keyed by
                              bounded label spaces (table/segment/server
                              names) stay clean.
  W016 non-durable-write     an `open(..., "w"/"wb")` whose target is a
                              durability artifact (path mentions checkpoint/
                              journal/snapshot/manifest/metadata, or the
                              enclosing function is a commit/persist path)
                              in a function with no tmp-fsync-replace
                              discipline (neither os.fsync + os.replace nor
                              the spi.filesystem durable_write_* helpers).
                              A crash mid-write then tears the committed
                              file — exactly the corruption class the
                              recovery paths quarantine.
  W017 unfenced-timing        wall-clock timing (`t0 = time.perf_counter()`
                              ... `dt = ... - t0`) brackets a call to a
                              jitted callable (a name assigned from
                              `jax.jit(...)` or decorated with @jit) with
                              no device fence (`block_until_ready` /
                              `device_get`) before the stop timestamp.
                              JAX dispatch is async — the subtraction then
                              times the enqueue, not the compute, and the
                              "measurement" silently reports dispatch
                              latency as kernel throughput.  Attribute
                              calls (`plan.fn(...)`) are out of scope:
                              engine code deliberately times dispatch cost
                              there (compile_ms capture).
  W018 blocking-in-dispatch   a blocking call (time.sleep, block_until_ready,
                              synchronous device_get/.item()/.tolist(),
                              socket recv/sendall/accept/connect) inside the
                              async batch-dispatch path: a method of a
                              *Batcher class, or a pump/_pump/
                              *dispatch_loop* function.  The batcher's
                              worker/pump drains EVERY key's pending groups —
                              one blocking call there head-of-line blocks
                              every coalesced query, exactly the stall the
                              async broker tier exists to avoid.
                              `Condition.wait` is the sanctioned deadline
                              wakeup and stays clean; device fences belong
                              in the submitting caller's thread
                              (Future.result) or the runner's collect.
  W019 unbounded-retry-loop   a `while` loop in cluster/ that re-issues a
                              server call (`.execute(...)` /
                              `.execute_batch(...)`) either without a
                              bounded backoff (no sleep/_sleep anywhere in
                              the loop body) or without routing the
                              abandoned attempt through the cancel-probe
                              path (an execute call missing the cancel=/
                              cancels= keyword).  A retry/hedge loop with
                              neither is a tight retry storm whose
                              abandoned attempts keep burning device time —
                              the r11 cooperative-cancel contract exists
                              precisely so a re-issued call's loser can be
                              killed between kernels.

Kernel bodies (W001/W002 scope) are functions the module jits: decorated
with @jax.jit / @partial(jax.jit, ...) or passed by name to jax.jit(...)
anywhere in the file.  Closure-jitted lambdas need dataflow analysis and
are out of scope — the repo convention is named kernels.

W002 additionally covers two Pallas-era shapes (ops/pallas_scan.py):
  * ANY `np.`/`numpy.` call inside a Pallas kernel body (a function passed
    by name to `pl.pallas_call(...)`) — Pallas kernels trace refs; a host
    numpy call there either fails to trace or silently constant-folds.
  * `.block_until_ready()` inside a for/while body — a per-launch fence
    serializes the double-buffered macro-batch pipeline
    (parallel/engine.py drains with one device_get instead).

W020 guards the bit-packed forward-index contract (segment/packing.py):
inside a Pallas kernel body, an `.astype(...)` whose receiver references a
packed-word operand (an identifier matching `packed`/`word`) WITHOUT a
`>>` lane-unpack anywhere in that receiver expression widens the packed
words to full dtype before the predicate/accumulate — spilling the
register-resident unpack back into a full-width HBM intermediate, which
forfeits the bandwidth the packing bought.  Shift first (`_lane_unpack`),
then cast the unpacked lanes.

W021 guards the tiered-storage staging contract (segment/residency.py): a
`jax.device_put(...)` whose shipped argument references a SEGMENT-SIZED
operand (an identifier matching codes/packed/values/nulls/mv_lengths/
column/segment) outside a staging-path function (name containing
`to_device` or `stage`) is a synchronous, unbudgeted host->device copy on
the serving path — it bypasses the residency manager's charge/evict
accounting AND stalls the caller for the full PCIe transfer instead of
riding the overlapped copy stream.  Small per-query params (literals,
bitmap words, stacked scalar pytrees) are fine: the rule keys on the
operand's name, not the call site.

W022 guards the leadership clock discipline (cluster/election.py): any
wall-clock `time.time()` arithmetic (+/-/compare, directly or through a
local alias) inside lease/election/fencing code — a function or class whose
name mentions lease/election/fence/promote/demote — or anywhere when the
same expression mixes `time.time()` with a lease/epoch-named identifier.
Lease deadlines and epoch-fence decisions MUST ride the injectable
(monotonic-backed) clock: an NTP step on the wall clock would depose a
healthy leader or immortalize a dead one, and no test can ever drive the
failover deterministically.  Sharper than W005: W005 only flags elapsed
subtraction/comparison, while a lease bug's signature is the ADDITION
(`deadline = time.time() + ttl`), which W005 deliberately ignores.

W025 guards the mesh-topology abstraction (parallel/mesh.py): a collective
(`lax.psum`/`pmin`/`pmax`/`all_gather`/`all_to_all`/`ppermute`/`axis_index`)
called with a bare axis-name string literal ("seg"/"replica"/"shard", or a
tuple literal of them) outside parallel/mesh.py hardcodes one mesh topology
into the call site.  Since the 2-D (replica x shard) scale-out, the axis an
exchange or combine runs over is decided by the mesh the engine was built
on — 1-D legacy ("seg",), 2-D capacity (both axes), or a replica row's own
1-D submesh — and combines must reduce hierarchically (shard/ICI first,
then replica/DCN).  A literal traces fine on the topology it was written
against and fails — or reduces over the wrong axis SUBSET, silently
producing per-row partial results — on the others.  Call sites must thread
the engine's `axis`/`axes` (or parallel/mesh constants/helpers) instead;
mesh.py itself, which defines the names, is exempt.

W023/W024 are the resource-lifecycle passes (analysis/lifecycle.py): W023
tracks the ledger open/close pairs (reserve->release, try_charge->uncharge,
try_fire->unfire, register->deregister, arm->disarm) and flags an opened
handle that neither escapes to a new owner nor closes on the function's
exception edges; W024 enforces condition-variable discipline (wait inside
a while-predicate loop; notify under the condition's lock).  They are the
static face of the concurrency model checker (analysis/model_check.py),
which proves the same pairings dynamically.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set

RULES: Dict[str, str] = {
    "W001": "float literal in jitted kernel (weak-type f32 promotion)",
    "W002": "host<->device sync inside jitted kernel",
    "W003": "jax.jit constructed per-iteration/per-call (recompiles)",
    "W004": "unlocked read-modify-write of shared state in cluster class",
    "W005": "wall-clock time.time() in elapsed-time math (use monotonic/perf_counter)",
    "W006": "except block in cluster/ swallows the exception without recording it",
    "W007": "metric/span name interpolates an unbounded value (cardinality explosion)",
    "W008": "literal-baked fingerprint() used as a plan-cache key (use shape_fingerprint)",
    "W015": "unbounded container growth on a cluster serving path (no bound/eviction)",
    "W016": "non-durable write to a durability path (no tmp-fsync-replace discipline)",
    "W017": "wall-clock timing around an async jitted dispatch without a device fence before the stop timestamp",
    "W018": "blocking call (sleep/device fence/socket I/O) inside an async batch-dispatch path",
    "W019": "retry/hedge loop re-issues a server call without bounded backoff or without the cancel-probe path",
    "W020": "packed words widened via .astype() in a Pallas kernel body before the lane unpack (shift first, then cast)",
    "W021": "synchronous jax.device_put of a segment-sized array outside the staging stream (route through the residency manager's budgeted charge)",
    "W022": "wall-clock time.time() arithmetic in lease/election/fencing code (use the injectable/monotonic clock)",
    "W025": "bare mesh-axis string literal passed to a collective outside parallel/mesh.py (use the engine's axis/axes or the mesh module's axis constants)",
    "W026": "controller discipline: direct write to a registry-managed serving knob outside a clamped KnobRegistry setter, or wall-clock use inside the autopilot (use the injected clock)",
    # interprocedural passes (analysis/races.py, analysis/device_sync.py —
    # run via analysis/engine.py over the whole package, not per-file):
    "W010": "lock-guarded attribute read/written without holding its lock",
    "W011": "lock-order cycle across lock acquisitions (deadlock risk)",
    "W012": "blocking call (sleep/sync/socket/device put) while holding a lock",
    "W013": "implicit device->host sync on the warm query path",
    "W014": "host control flow branches on a device value in the warm path",
    # resource-lifecycle passes (analysis/lifecycle.py):
    "W023": "paired resource (reserve/release, try_charge/uncharge, try_fire/unfire, register/deregister, arm/disarm) opened but not closed on exception edges and never handed off",
    "W024": "condition-variable discipline: wait outside a while-predicate loop, or notify without holding the condition's lock (lost-wakeup shapes)",
}

_HOST_SYNC_ATTRS = frozenset({"item", "block_until_ready", "device_get", "tolist"})
_HOST_MODULES = frozenset({"np", "numpy"})


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str
    # optional enrichment from the interprocedural passes (analysis/engine.py):
    # a fix hint and the enclosing symbol ("Class.method") — empty for the
    # per-file rules so the greppable str() form stays byte-stable
    hint: str = ""
    symbol: str = ""

    def __str__(self) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            s += f" [fix: {self.hint}]"
        return s

    def to_dict(self) -> Dict[str, object]:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
            "hint": self.hint,
            "symbol": self.symbol,
        }


def _is_jit_func(node: ast.AST) -> bool:
    """ast node that refers to jax.jit (Name 'jit' or Attribute '*.jit')."""
    if isinstance(node, ast.Attribute):
        return node.attr == "jit"
    return isinstance(node, ast.Name) and node.id == "jit"


def _jitted_function_names(tree: ast.AST) -> Set[str]:
    """Names passed to jax.jit(...) as a bare Name anywhere in the module."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_func(node.func):
            for a in node.args[:1]:
                if isinstance(a, ast.Name):
                    out.add(a.id)
    return out


def _has_jit_decorator(fn: ast.FunctionDef) -> bool:
    for d in fn.decorator_list:
        if _is_jit_func(d):
            return True
        if isinstance(d, ast.Call):
            if _is_jit_func(d.func):
                return True
            # @partial(jax.jit, ...)
            if (
                isinstance(d.func, ast.Name)
                and d.func.id == "partial"
                and d.args
                and _is_jit_func(d.args[0])
            ):
                return True
    return False


def _is_pallas_call(node: ast.AST) -> bool:
    """ast node referring to pallas_call (pl.pallas_call / bare name)."""
    if isinstance(node, ast.Attribute):
        return node.attr == "pallas_call"
    return isinstance(node, ast.Name) and node.id == "pallas_call"


def _pallas_kernel_names(tree: ast.AST) -> Set[str]:
    """Names passed to pallas_call(...) as a bare Name anywhere in the
    module — the same by-name convention as _jitted_function_names."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_pallas_call(node.func):
            for a in node.args[:1]:
                if isinstance(a, ast.Name):
                    out.add(a.id)
    return out


_PACKED_OPERAND = re.compile(r"packed|word", re.IGNORECASE)


def _references_packed_operand(node: ast.AST) -> bool:
    """Any identifier in the expression smells like a packed-word operand."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _PACKED_OPERAND.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and _PACKED_OPERAND.search(sub.attr):
            return True
    return False


def _has_rshift(node: ast.AST) -> bool:
    return any(
        isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.RShift)
        for sub in ast.walk(node)
    )


class _PallasKernelRules(ast.NodeVisitor):
    """W002 + W020 inside one Pallas kernel body.

    W002: any host numpy call.  Stricter than the jit-kernel rule (which
    allows np scalars like np.int32(0) as weak-type anchors): a Pallas
    kernel body manipulates Refs, where every np.* call is at best a
    silent constant fold and at worst a trace error — jnp/lax are the only
    legal vocabularies.

    W020: `.astype(...)` on a packed-word operand (identifier matching
    packed/word) with no `>>` in the receiver — the lane unpack must
    happen BEFORE any widening cast, or the packed words materialize at
    full dtype and the bandwidth saving is lost.  A shift in the receiver
    is the unpack already having happened, so that stays clean."""

    def __init__(self, path: str, findings: List[Finding]):
        self.path = path
        self.findings = findings

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in _HOST_MODULES
        ):
            self.findings.append(
                Finding(
                    self.path, node.lineno, "W002",
                    f"{f.value.id}.{f.attr}() is a host numpy call inside a Pallas kernel body",
                )
            )
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "astype"
            and _references_packed_operand(f.value)
            and not _has_rshift(f.value)
        ):
            self.findings.append(
                Finding(
                    self.path, node.lineno, "W020",
                    "packed words widened via .astype() before the lane "
                    "unpack — shift (>>) the lanes out first, then cast",
                )
            )
        self.generic_visit(node)


def _check_sync_in_loop(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """W002: .block_until_ready() inside a for/while body — a per-launch
    fence serializes the macro-batch dispatch pipeline (the double-buffer
    loop must drain via device_get of the oldest launch instead).  Function
    bodies reset the loop scope, same as W003: a def inside a loop runs
    when called, not per iteration."""

    def walk(node: ast.AST, depth: int) -> None:
        is_loop = isinstance(node, (ast.For, ast.While))
        for child in ast.iter_child_nodes(node):
            nd = (
                0
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef))
                else depth + (1 if is_loop else 0)
            )
            if (
                nd > 0
                and isinstance(child, ast.Call)
                and isinstance(child.func, ast.Attribute)
                and child.func.attr == "block_until_ready"
            ):
                findings.append(
                    Finding(
                        path, child.lineno, "W002",
                        "per-launch .block_until_ready() in a loop serializes the dispatch pipeline",
                    )
                )
            walk(child, nd)

    walk(tree, 0)


def _is_lock_name(name: str) -> bool:
    # condition variables count: `with self._cv:` acquires the underlying lock
    low = name.lower()
    return "lock" in low or "cond" in low or low.lstrip("_") == "cv"


def _mentions_lock(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and _is_lock_name(n.attr):
            return True
        if isinstance(n, ast.Name) and _is_lock_name(n.id):
            return True
    return False


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for `self.x`, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _reads_self_attr(node: ast.AST, attr: str) -> bool:
    for n in ast.walk(node):
        if _self_attr(n) == attr:
            return True
    return False


class _KernelRules(ast.NodeVisitor):
    """W001 + W002 inside one jitted kernel body."""

    def __init__(self, path: str, findings: List[Finding]):
        self.path = path
        self.findings = findings

    def _flag(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(self.path, getattr(node, "lineno", 0), rule, msg))

    def visit_BinOp(self, node: ast.BinOp) -> None:
        for op in (node.left, node.right):
            if isinstance(op, ast.Constant) and type(op.value) is float:
                self._flag("W001", op, f"float literal {op.value!r} in kernel arithmetic")
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        for op in [node.left] + list(node.comparators):
            if isinstance(op, ast.Constant) and type(op.value) is float:
                self._flag("W001", op, f"float literal {op.value!r} in kernel comparison")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_SYNC_ATTRS:
                self._flag("W002", node, f".{f.attr}() syncs host<->device inside a kernel")
            elif (
                f.attr == "asarray"
                and isinstance(f.value, ast.Name)
                and f.value.id in _HOST_MODULES
            ):
                self._flag("W002", node, f"{f.value.id}.asarray() materializes on host inside a kernel")
        self.generic_visit(node)


def _check_w003(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    loop_depth_of: Dict[int, int] = {}

    def walk(node: ast.AST, depth: int) -> None:
        is_loop = isinstance(node, (ast.For, ast.While))
        for child in ast.iter_child_nodes(node):
            # function/class bodies reset the loop scope: a def inside a
            # loop compiles when CALLED, not per loop iteration
            nd = 0 if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)) else depth + (1 if is_loop else 0)
            if isinstance(child, ast.Call) and _is_jit_func(child.func) and nd > 0:
                findings.append(
                    Finding(path, child.lineno, "W003", "jax.jit(...) constructed inside a loop body")
                )
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Call)
                and _is_jit_func(child.func.func)
            ):
                findings.append(
                    Finding(path, child.lineno, "W003", "jax.jit(f)(...) jit-then-call never caches")
                )
            walk(child, nd)

    walk(tree, 0)


def _check_w004(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Unlocked RMW on shared self attributes in cluster/ classes."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) or fn.name == "__init__":
                continue
            # local aliases of self attrs (`b = self._buckets.get(k)` then
            # `b[0] = ...` is still an RMW on the shared dict's values)
            aliases: Dict[str, str] = {}
            locked_lines: List[range] = []
            for n in ast.walk(fn):
                if isinstance(n, ast.With) and any(_mentions_lock(i.context_expr) for i in n.items):
                    locked_lines.append(range(n.lineno, (n.end_lineno or n.lineno) + 1))

            def under_lock(node: ast.AST) -> bool:
                ln = getattr(node, "lineno", 0)
                return any(ln in r for r in locked_lines)

            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
                    src = n.value
                    if isinstance(src, ast.Call) and isinstance(src.func, ast.Attribute):
                        src = src.func.value  # self.x.get(...) -> self.x
                    if isinstance(src, ast.Subscript):
                        src = src.value
                    attr = _self_attr(src)
                    if attr is not None and not under_lock(n):
                        aliases[n.targets[0].id] = attr

            def shared_target(t: ast.AST) -> Optional[str]:
                if isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        return attr
                    if isinstance(t.value, ast.Name) and t.value.id in aliases:
                        return aliases[t.value.id]
                return _self_attr(t)

            for n in ast.walk(fn):
                if isinstance(n, ast.AugAssign):
                    attr = shared_target(n.target)
                    if attr is not None and not under_lock(n):
                        findings.append(
                            Finding(
                                path, n.lineno, "W004",
                                f"unlocked `self.{attr}` read-modify-write in {cls.name}.{fn.name}",
                            )
                        )
                elif isinstance(n, ast.Assign):
                    for t in n.targets:
                        attr = shared_target(t) if isinstance(t, ast.Subscript) else None
                        if attr is None or under_lock(n):
                            continue
                        # writing through an ALIAS of a shared container is an
                        # RMW by construction (the alias bind read it); direct
                        # self.X[k] = v writes only count when the value reads
                        # X back (plain inserts are setup, not RMW)
                        via_alias = (
                            isinstance(t.value, ast.Name) and t.value.id in aliases
                        )
                        reads = via_alias or _reads_self_attr(n.value, attr) or any(
                            isinstance(x, ast.Name) and aliases.get(x.id) == attr
                            for x in ast.walk(n.value)
                        )
                        if reads:
                            findings.append(
                                Finding(
                                    path, n.lineno, "W004",
                                    f"unlocked `self.{attr}` read-modify-write in {cls.name}.{fn.name}",
                                )
                            )


def _is_time_time_call(node: ast.AST) -> bool:
    """`time.time()` — the wall clock (bare `time()` is ambiguous, skipped)."""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "time"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "time"
    )


def _contains_time_time(node: ast.AST, aliases: Set[str]) -> bool:
    for n in ast.walk(node):
        if _is_time_time_call(n):
            return True
        if isinstance(n, ast.Name) and n.id in aliases:
            return True
    return False


def _check_w005(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Wall-clock elapsed-time math: time.time() (or a local assigned
    exactly `time.time()`) used as an operand of a subtraction or
    comparison.  `int(time.time() * 1000)` stored as an epoch timestamp is
    deliberately NOT tracked through the alias — epoch math against data
    timestamps (retention windows, segment time ranges) is correct use."""

    def scope_nodes(body: List[ast.stmt]):
        """Walk a scope without descending into nested function bodies
        (those get their own pass with their own aliases)."""
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested scope: gets its own pass with its own aliases
            stack.extend(ast.iter_child_nodes(n))

    def scan_scope(body: List[ast.stmt]) -> None:
        aliases: Set[str] = set()
        nodes = list(scope_nodes(body))
        for n in nodes:  # collect aliases first: use can precede def in walk order
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and _is_time_time_call(n.value)
            ):
                aliases.add(n.targets[0].id)
        if not aliases and not any(_is_time_time_call(n) for n in nodes):
            return
        for n in nodes:
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                if _contains_time_time(n.left, aliases) or _contains_time_time(n.right, aliases):
                    findings.append(
                        Finding(
                            path, n.lineno, "W005",
                            "time.time() in elapsed-time subtraction — use time.monotonic()/perf_counter()",
                        )
                    )
            elif isinstance(n, ast.Compare):
                if any(_contains_time_time(op, aliases) for op in [n.left] + list(n.comparators)):
                    findings.append(
                        Finding(
                            path, n.lineno, "W005",
                            "time.time() in a time comparison — use time.monotonic()/perf_counter()",
                        )
                    )

    scan_scope(getattr(tree, "body", []))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node.body)


# lease/election/fencing scope: the code whose clock MUST be injectable
_W022_SCOPE = re.compile(r"lease|election|fence|fencing|promote|demote|deposed", re.I)
# identifiers whose arithmetic against the wall clock marks a fencing bug
# even outside a scope-named function (max_epoch, lease_deadline, expiresAt)
_W022_IDENT = re.compile(r"lease|expires|(^|_)epoch", re.I)


def _check_w022(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """W022: wall-clock time.time() arithmetic in lease-deadline or
    epoch-compare code paths.  Two triggers:

      * any +/-/compare involving time.time() (or an exact local alias)
        inside a function or class whose name matches lease/election/
        fence/promote/demote — that code's clock must be the injectable
        one, full stop;
      * anywhere else, a +/-/compare that MIXES time.time() with a
        lease/epoch-named identifier (``entry_epoch > time.time() - ttl``).

    Epoch *timestamp* stamping (``int(time.time() * 1000)``) is
    multiplication, not flagged; retention math over data timestamps never
    touches time.time() in the same expression and stays clean."""

    def scope_nodes(body: List[ast.stmt]):
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested scope: gets its own pass
            stack.extend(ast.iter_child_nodes(n))

    def names_match(node: ast.AST) -> bool:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and _W022_IDENT.search(n.id):
                return True
            if isinstance(n, ast.Attribute) and _W022_IDENT.search(n.attr):
                return True
        return False

    def scan(body: List[ast.stmt], scoped: bool) -> None:
        nodes = list(scope_nodes(body))
        aliases: Set[str] = set()
        for n in nodes:
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and _is_time_time_call(n.value)
            ):
                aliases.add(n.targets[0].id)
        for n in nodes:
            if isinstance(n, ast.BinOp) and isinstance(n.op, (ast.Add, ast.Sub)):
                operands = [n.left, n.right]
            elif isinstance(n, ast.Compare):
                operands = [n.left] + list(n.comparators)
            else:
                continue
            if not any(_contains_time_time(op, aliases) for op in operands):
                continue
            if scoped:
                findings.append(
                    Finding(
                        path, n.lineno, "W022",
                        "wall-clock time.time() arithmetic in lease/election code — "
                        "use the injectable clock (LeaseManager.now / time.monotonic)",
                    )
                )
            elif any(names_match(op) for op in operands):
                findings.append(
                    Finding(
                        path, n.lineno, "W022",
                        "time.time() mixed with a lease/epoch identifier — fencing "
                        "decisions must ride the injectable/monotonic clock",
                    )
                )

    def collect(node: ast.AST, enclosing_scoped: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                collect(child, enclosing_scoped or bool(_W022_SCOPE.search(child.name)))
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scoped = enclosing_scoped or bool(_W022_SCOPE.search(child.name))
                scan(child.body, scoped)
                collect(child, scoped)
            else:
                collect(child, enclosing_scoped)

    scan(getattr(tree, "body", []), False)
    collect(tree, False)


# registry-managed serving knob attributes (cluster/autopilot.py SPECS):
# runtime mutation must go through a clamped KnobRegistry setter, never a
# bare attribute write that skips the clamp bounds and the atomic swap
_W026_KNOB_ATTRS = frozenset(
    {"wait_ms", "pipeline_depth", "staging_depth", "budget_pct", "quantile_mult"}
)
# wall clocks forbidden inside the autopilot: the controller's whole test
# story rides the injected clock (threads.monotonic or a ctor fake)
_W026_WALL_CLOCKS = frozenset({"time", "monotonic", "perf_counter"})


def _is_wall_clock_call(node: ast.AST) -> bool:
    """`time.time()` / `time.monotonic()` / `time.perf_counter()` — module
    attribute calls only, so `threads.monotonic()` (the injection seam)
    and `self.clock()` stay clean."""
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _W026_WALL_CLOCKS
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "time"
    )


def _check_w026(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """W026 (controller discipline), two triggers:

      * an Assign/AugAssign whose target is a `<obj>.<knob>` attribute for
        a registry-managed knob name, outside `__init__` (construction
        wires defaults) and outside a property-setter body (the sanctioned
        pin-the-override path) — runtime knob mutation must go through a
        clamped KnobRegistry setter so the static ceilings and the atomic
        snapshot discipline hold;
      * in an autopilot module (path contains "autopilot"), any
        `time.time()`/`time.monotonic()`/`time.perf_counter()` call — the
        control loop must read the INJECTED clock (`threads.monotonic` or
        the ctor's fake) or the deterministic scheduler cannot drive it."""

    def is_exempt_fn(fn: ast.AST) -> bool:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        if fn.name == "__init__":
            return True
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Attribute) and dec.attr == "setter":
                return True
        return False

    def scan_writes(body: List[ast.stmt]) -> None:
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not is_exempt_fn(n):
                    scan_writes(n.body)
                continue
            targets: List[ast.AST] = []
            if isinstance(n, ast.Assign):
                targets = list(n.targets)
            elif isinstance(n, ast.AugAssign):
                targets = [n.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr in _W026_KNOB_ATTRS:
                    findings.append(
                        Finding(
                            path, n.lineno, "W026",
                            f"direct write to registry-managed knob `.{t.attr}` "
                            "outside a clamped KnobRegistry setter — route runtime "
                            "tuning through autopilot.knobs().set() so clamp bounds "
                            "and the atomic knob snapshot hold",
                        )
                    )
            stack.extend(ast.iter_child_nodes(n))

    scan_writes(getattr(tree, "body", []))

    if "autopilot" in os.path.basename(path):
        for n in ast.walk(tree):
            if _is_wall_clock_call(n):
                findings.append(
                    Finding(
                        path, n.lineno, "W026",
                        f"wall-clock time.{n.func.attr}() inside the autopilot — "
                        "the control loop must use its injected clock "
                        "(threads.monotonic / the ctor's fake) so the "
                        "deterministic scheduler and fake-clock tests can drive it",
                    )
                )


def _check_w006(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Swallowed exceptions: a handler with no Raise and no Call anywhere
    in its body drops the fault invisibly (`except: pass`, `except:
    continue`).  Any call — logging, metrics, recording onto a stats
    object, even a send — counts as surfacing it."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        has_signal = any(
            isinstance(n, (ast.Raise, ast.Call)) for n in ast.walk(ast.Module(body=node.body, type_ignores=[]))
        )
        if not has_signal:
            findings.append(
                Finding(
                    path, node.lineno, "W006",
                    "except block swallows the exception (no raise, no log/metrics/record call)",
                )
            )


_METRIC_NAME_SINKS = frozenset({"counter", "gauge", "timer", "histogram", "span"})
_UNBOUNDED_HINTS = ("sql", "query", "qid", "uuid", "fingerprint", "text")


def _unbounded_hint(name: str) -> bool:
    """Identifier that smells like a per-request value: sql text, query /
    request ids, uuids, fingerprints.  Table/segment/server names are
    bounded label spaces and interpolate freely."""
    low = name.lower()
    return low == "id" or low.endswith("_id") or any(h in low for h in _UNBOUNDED_HINTS)


def _check_w007(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Metric/span names from f-strings interpolating unbounded values:
    `METRICS.counter(f"lat.{sql}")` mints one counter PER DISTINCT QUERY —
    the registry (and any Prometheus scraper behind it) grows without
    bound.  Scope is the name argument of the registry factories and
    trace spans; only the interpolated expressions are inspected, so
    `f"server.segmentBytes.{table}"` stays clean."""
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_NAME_SINKS
            and node.args
            and isinstance(node.args[0], ast.JoinedStr)
        ):
            continue
        for part in node.args[0].values:
            if not isinstance(part, ast.FormattedValue):
                continue
            for n in ast.walk(part.value):
                name = n.id if isinstance(n, ast.Name) else (
                    n.attr if isinstance(n, ast.Attribute) else None
                )
                if name is not None and _unbounded_hint(name):
                    findings.append(
                        Finding(
                            path, node.lineno, "W007",
                            f"metric/span name interpolates unbounded value {name!r} "
                            f"in .{node.func.attr}(...) — one series per distinct value",
                        )
                    )
                    break


def _contains_fingerprint_call(node: ast.AST) -> bool:
    """An expression containing a `.fingerprint()` call — the FULL form that
    bakes literal values.  `.shape_fingerprint()` is a different attribute
    and deliberately does not match."""
    for n in ast.walk(node):
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr == "fingerprint"
        ):
            return True
    return False


def _is_plan_cache_name(node: ast.AST) -> bool:
    """A name/attribute that IS a plan cache by repo convention
    (`_PLAN_CACHE`, `self._plan_cache`, ...).  Result caches, slow logs and
    audit maps legitimately hold full fingerprints and never match."""
    name = node.attr if isinstance(node, ast.Attribute) else (
        node.id if isinstance(node, ast.Name) else None
    )
    return name is not None and "plan_cache" in name.lower()


def _check_w008(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Literal-baked plan-cache keys: `.fingerprint()` output reaching a
    plan-cache subscript or .get/.put key, directly or via one local
    assignment (`key = (ctx.fingerprint(), ...)` then `cache.get(key)`).
    Every distinct literal then retraces an identical kernel shape — the
    exact recompile storm shape_fingerprint() exists to prevent."""

    def scan_scope(body: List[ast.stmt]) -> None:
        nodes: List[ast.AST] = []
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            nodes.append(n)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested scope: its own pass, its own taints
            stack.extend(ast.iter_child_nodes(n))
        tainted: Set[str] = set()
        for n in nodes:
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and _contains_fingerprint_call(n.value)
            ):
                tainted.add(n.targets[0].id)

        def literal_bearing(expr: ast.AST) -> bool:
            return _contains_fingerprint_call(expr) or (
                isinstance(expr, ast.Name) and expr.id in tainted
            )

        for n in nodes:
            if (
                isinstance(n, ast.Subscript)
                and _is_plan_cache_name(n.value)
                and literal_bearing(n.slice)
            ):
                findings.append(
                    Finding(
                        path, n.lineno, "W008",
                        "literal-baked fingerprint() in plan-cache key — "
                        "key on shape_fingerprint() so literals parameterize",
                    )
                )
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("get", "put", "setdefault", "pop")
                and _is_plan_cache_name(n.func.value)
                and n.args
                and literal_bearing(n.args[0])
            ):
                findings.append(
                    Finding(
                        path, n.lineno, "W008",
                        "literal-baked fingerprint() in plan-cache key — "
                        "key on shape_fingerprint() so literals parameterize",
                    )
                )

    scan_scope(getattr(tree, "body", []))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node.body)


_W015_GROW = frozenset({"append", "extend", "appendleft", "add", "insert"})
_W015_EVICT = frozenset({"pop", "popitem", "popleft", "clear", "discard", "remove"})
_W015_DICTLIKE = frozenset({"dict", "OrderedDict", "defaultdict", "Counter"})
_W015_SEQLIKE = frozenset({"list", "set", "deque"})
# method-name fragments marking the request-serving path — growth in setup /
# registration / teardown methods is a topology-sized one-shot, not a leak
_W015_SERVING = (
    "execute", "query", "handle", "scatter", "admit",
    "record", "check", "serve", "request", "do_",
)


def _check_w015(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Unbounded container growth on a serving path: an attribute born
    unbounded in `__init__` (list/set/dict literal, `deque()` with no
    maxlen) that a serving-named method grows per request — `.append()`
    and friends, or a dict write keyed by an unbounded value (query id,
    sql, uuid; W007's hint list) — while NOTHING in the class ever evicts.
    Eviction evidence is any `.pop/.clear/.discard/...` call on the
    attribute, a `del self.x[...]`, or a reassignment outside `__init__`.
    Dict writes keyed by bounded label spaces (table/segment/server names)
    never flag: only per-request key spaces grow without bound."""
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        # --- pass 1: containers created unbounded in __init__ ------------
        unbounded: Dict[str, str] = {}  # attr -> "dict" | "seq"
        init = next(
            (n for n in cls.body if isinstance(n, ast.FunctionDef) and n.name == "__init__"),
            None,
        )
        if init is None:
            continue
        for n in ast.walk(init):
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(n, ast.Assign):
                targets, value = n.targets, n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                targets, value = [n.target], n.value
            if value is None:
                continue
            kind: Optional[str] = None
            if isinstance(value, ast.Dict):
                kind = "dict"
            elif isinstance(value, (ast.List, ast.Set)):
                kind = "seq"
            elif isinstance(value, ast.Call):
                fn = value.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None
                )
                if fname in _W015_DICTLIKE:
                    kind = "dict"
                elif fname in _W015_SEQLIKE:
                    if fname == "deque" and any(k.arg == "maxlen" for k in value.keywords):
                        kind = None  # bounded ring buffer
                    else:
                        kind = "seq"
            if kind is None:
                continue
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    unbounded[attr] = kind
        if not unbounded:
            continue
        # --- pass 2: eviction evidence anywhere in the class exempts -----
        for n in ast.walk(cls):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _W015_EVICT
            ):
                attr = _self_attr(n.func.value)
                if attr is not None:
                    unbounded.pop(attr, None)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    base = t.value if isinstance(t, ast.Subscript) else t
                    attr = _self_attr(base)
                    if attr is not None:
                        unbounded.pop(attr, None)
        for meth in cls.body:
            if not isinstance(meth, ast.FunctionDef) or meth.name == "__init__":
                continue
            for n in ast.walk(meth):
                targets = (
                    n.targets if isinstance(n, ast.Assign)
                    else [n.target] if isinstance(n, (ast.AnnAssign, ast.AugAssign))
                    else []
                )
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        unbounded.pop(attr, None)  # rebuilt/reset elsewhere
        if not unbounded:
            continue
        # --- pass 3: growth inside serving-named methods -----------------
        for meth in cls.body:
            if not isinstance(meth, ast.FunctionDef):
                continue
            low = meth.name.lower()
            if not any(h in low for h in _W015_SERVING):
                continue
            for n in ast.walk(meth):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in _W015_GROW
                ):
                    attr = _self_attr(n.func.value)
                    if attr in unbounded and unbounded[attr] != "dict":
                        findings.append(
                            Finding(
                                path, n.lineno, "W015",
                                f"self.{attr}.{n.func.attr}(...) in serving method "
                                f"{meth.name!r} grows without bound — no eviction "
                                f"anywhere in class {cls.name!r}",
                            )
                        )
                # dict growth: subscript-store or setdefault keyed by an
                # unbounded (per-request) value
                key: Optional[ast.expr] = None
                attr = None
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Subscript):
                            a = _self_attr(t.value)
                            if a in unbounded and unbounded[a] == "dict":
                                key, attr = t.slice, a
                elif (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "setdefault"
                    and n.args
                ):
                    a = _self_attr(n.func.value)
                    if a in unbounded and unbounded[a] == "dict":
                        key, attr = n.args[0], a
                if key is None:
                    continue
                keyed_unbounded = False
                for kn in ast.walk(key):
                    name = kn.id if isinstance(kn, ast.Name) else (
                        kn.attr if isinstance(kn, ast.Attribute) else None
                    )
                    if name is not None and _unbounded_hint(name):
                        keyed_unbounded = True
                        break
                if keyed_unbounded:
                    findings.append(
                        Finding(
                            path, n.lineno, "W015",
                            f"self.{attr}[...] keyed by a per-request value in "
                            f"serving method {meth.name!r} grows without bound — "
                            f"no eviction anywhere in class {cls.name!r}",
                        )
                    )


# path fragments naming durability artifacts: a torn write here IS data loss
_W016_PATH_HINTS = ("checkpoint", "journal", "snapshot", "manifest", "metadata")
# function-name fragments marking commit/persist paths
_W016_FUNC_HINTS = ("commit", "checkpoint", "journal", "snapshot", "persist")


def _check_w016(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Durable-write discipline: a bare `open(target, "w"/"wb")` aimed at a
    durability artifact must live in a function that commits via
    tmp-fsync-replace (os.fsync AND os.replace both called, in any order —
    the write-ahead idiom) or delegates to the spi.filesystem
    durable_write_* helpers.  Without that, a crash mid-write leaves a torn
    half-file where the committed state used to be.  Scope is the enclosing
    function: the rule checks discipline where the write happens, so a
    clean helper used from many callers stays clean everywhere."""

    def scope_nodes(body: List[ast.stmt]) -> List[ast.AST]:
        nodes: List[ast.AST] = []
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            nodes.append(n)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested function: its own discipline, its own pass
            stack.extend(ast.iter_child_nodes(n))
        return nodes

    def call_name(n: ast.AST) -> Optional[str]:
        if not isinstance(n, ast.Call):
            return None
        fn = n.func
        if isinstance(fn, ast.Name):
            return fn.id
        if isinstance(fn, ast.Attribute):
            return fn.attr
        return None

    def write_mode(call: ast.Call) -> Optional[str]:
        mode = call.args[1] if len(call.args) > 1 else next(
            (k.value for k in call.keywords if k.arg == "mode"), None
        )
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return mode.value
        return None

    def scan_scope(func_name: str, body: List[ast.stmt]) -> None:
        nodes = scope_nodes(body)
        disciplined = False
        has_fsync = has_replace = False
        for n in nodes:
            name = call_name(n)
            if name == "fsync":
                has_fsync = True
            elif name == "replace":
                has_replace = True
            elif name is not None and name.startswith("durable_write"):
                disciplined = True
        disciplined = disciplined or (has_fsync and has_replace)
        if disciplined:
            return
        low_fn = func_name.lower()
        fn_is_commit_path = any(h in low_fn for h in _W016_FUNC_HINTS)
        for n in nodes:
            if call_name(n) != "open" or not n.args:
                continue
            mode = write_mode(n)
            if mode is None or not mode.startswith("w"):
                continue
            target = ast.unparse(n.args[0]).lower()
            if fn_is_commit_path or any(h in target for h in _W016_PATH_HINTS):
                findings.append(
                    Finding(
                        path, n.lineno, "W016",
                        f"open({ast.unparse(n.args[0])}, {mode!r}) writes a durability "
                        f"artifact in place — commit via tmp + os.fsync + os.replace "
                        f"(or spi.filesystem.durable_write_*) so a crash can't tear it",
                    )
                )

    scan_scope("<module>", getattr(tree, "body", []))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node.name, node.body)


_W017_CLOCK_FUNCS = frozenset({"perf_counter", "monotonic"})
_W017_FENCE_FUNCS = frozenset({"block_until_ready", "device_get"})


def _is_perf_clock_call(node: ast.AST) -> bool:
    """Call to time.perf_counter / time.monotonic (module attr or bare)."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (fn.id if isinstance(fn, ast.Name) else None)
    return name in _W017_CLOCK_FUNCS


def _w017_dispatch_names(tree: ast.AST) -> Set[str]:
    """Names that ARE jitted callables when called: `f = jax.jit(...)`
    assignment targets and @jit-decorated function names.  (Distinct from
    _jitted_function_names, which collects the UNDERLYING function passed
    to jit — calling that name directly runs eagerly and times fine.)"""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and _is_jit_func(node.value.func)
        ):
            out.add(node.targets[0].id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and _has_jit_decorator(node):
            out.add(node.name)
    return out


def _check_w017(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Unfenced wall-clock timing of an async dispatch: between a
    perf_counter/monotonic timer start and the subtraction that stops it,
    a jitted callable is invoked by name with no block_until_ready /
    device_get before the stop.  The elapsed time then measures enqueue
    latency, not device compute — the bench-number class of bug.

    Deliberately narrow to keep the package lint-clean where timing
    dispatch IS the point: only bare-Name calls to known-jitted names
    count as dispatches (engine code calling `plan.fn(...)` to measure
    compile/dispatch cost is an attribute call and out of scope), and a
    fence anywhere between the dispatch and the stop — including wrapping
    the dispatch itself, `device_get(f(x))` — clears it."""
    dispatch_names = _w017_dispatch_names(tree)
    if not dispatch_names:
        return

    def scope_nodes(body: List[ast.stmt]) -> List[ast.AST]:
        nodes: List[ast.AST] = []
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            nodes.append(n)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # nested scope: its own timers, its own pass
            stack.extend(ast.iter_child_nodes(n))
        return nodes

    def scan_scope(body: List[ast.stmt]) -> None:
        nodes = scope_nodes(body)
        starts: List[tuple] = []  # (lineno, timer name)
        timer_names: Set[str] = set()
        dispatches: List[int] = []
        fences: List[int] = []
        for n in nodes:
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and _is_perf_clock_call(n.value)
            ):
                starts.append((n.lineno, n.targets[0].id))
                timer_names.add(n.targets[0].id)
            elif isinstance(n, ast.Call):
                fn = n.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None
                )
                if name in _W017_FENCE_FUNCS:
                    fences.append(n.lineno)
                elif isinstance(fn, ast.Name) and fn.id in dispatch_names:
                    dispatches.append(n.lineno)
        if not timer_names or not dispatches:
            return
        for n in nodes:
            if not (isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub)):
                continue
            used = {
                x.id for x in ast.walk(n) if isinstance(x, ast.Name) and x.id in timer_names
            }
            for tname in used:
                begins = [ln for ln, name in starts if name == tname and ln <= n.lineno]
                if not begins:
                    continue
                begin = max(begins)
                between = [d for d in dispatches if begin < d <= n.lineno]
                if not between:
                    continue
                last_dispatch = max(between)
                if any(last_dispatch <= f <= n.lineno for f in fences):
                    continue
                findings.append(
                    Finding(
                        path, n.lineno, "W017",
                        f"elapsed-time stop for timer '{tname}' after a jitted dispatch "
                        f"(line {last_dispatch}) with no block_until_ready/device_get fence — "
                        f"async dispatch means this times the enqueue, not the compute",
                    )
                )
                break  # one finding per stop expression

    scan_scope(getattr(tree, "body", []))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node.body)


_SUPPRESS_MARK = "pinot-lint:"


def parse_suppressions(src: str) -> Dict[int, Optional[Set[str]]]:
    """Per-line `# pinot-lint: disable=W0xx[,W0yy]` markers.

    Returns {lineno: set of suppressed rule ids} — the value None means
    every rule is suppressed on that line (`disable=all`).  Honored by the
    per-file rules (lint_source) and the interprocedural passes (engine).
    """
    out: Dict[int, Optional[Set[str]]] = {}
    for lineno, text in enumerate(src.splitlines(), start=1):
        if _SUPPRESS_MARK not in text:
            continue
        tail = text.split(_SUPPRESS_MARK, 1)[1]
        if "disable=" not in tail:
            continue
        spec = tail.split("disable=", 1)[1].split("#", 1)[0].strip()
        if not spec:
            continue
        if spec.lower() == "all":
            out[lineno] = None
        else:
            out[lineno] = {r.strip().upper() for r in spec.split(",") if r.strip()}
    return out


def is_suppressed(f: Finding, suppressions: Dict[int, Optional[Set[str]]]) -> bool:
    rules = suppressions.get(f.line, "absent")
    if rules == "absent":
        return False
    return rules is None or f.rule in rules


_W018_BLOCKING_ATTRS = frozenset({
    "block_until_ready", "device_get", "recv", "recv_into", "sendall",
    "accept", "connect", "create_connection", "item", "tolist",
})


def _check_w018(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """Blocking call inside the async batch-dispatch path.  Scope: methods
    of classes named *Batcher*, plus functions named pump/_pump or
    containing "dispatch_loop".  These run under (or are the tick of) the
    coalescing scheduler — a sleep, device fence, host-sync (.item/.tolist)
    or socket wait there stalls every key's pending groups at once.
    Condition.wait (the timed wakeup) is deliberately out of the blocking
    set: it is how the worker sleeps WITHOUT holding up a flush."""
    scopes: List[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and "Batcher" in node.name:
            scopes.extend(
                n for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in ("pump", "_pump") or "dispatch_loop" in node.name:
                scopes.append(node)
    seen: Set[int] = set()
    for fn in scopes:
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            blocked = None
            if isinstance(f, ast.Name) and f.id == "sleep":
                blocked = "sleep"
            elif isinstance(f, ast.Attribute):
                if f.attr == "sleep" or f.attr in _W018_BLOCKING_ATTRS:
                    blocked = f.attr
            if blocked:
                findings.append(Finding(
                    path, n.lineno, "W018",
                    f"blocking call `{blocked}` inside async batch-dispatch "
                    f"path `{fn.name}` — head-of-line blocks every coalesced query",
                ))


_W019_SERVER_CALLS = frozenset({"execute", "execute_batch"})


def _check_w019(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """W019: retry/hedge loop discipline.  A `while` loop that (re-)issues
    server calls — `.execute(...)` / `.execute_batch(...)` — is the failover
    or hedging shape; it must (a) bound its re-issue rate with a backoff
    (some sleep/_sleep call inside the loop body) and (b) route every server
    call through the cooperative-cancel contract (cancel=/cancels= keyword),
    so an abandoned attempt can be killed between kernels instead of burning
    device time to completion.  `for` loops are exempt: a fan-out over an
    assignment is not a retry."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.While):
            continue
        server_calls = []
        has_backoff = False
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr in _W019_SERVER_CALLS:
                server_calls.append(n)
            if (isinstance(f, ast.Name) and f.id in ("sleep", "_sleep")) or (
                isinstance(f, ast.Attribute) and f.attr in ("sleep", "_sleep")
            ):
                has_backoff = True
        if not server_calls:
            continue
        if not has_backoff:
            findings.append(Finding(
                path, node.lineno, "W019",
                "retry loop re-issues a server call with no bounded backoff "
                "(no sleep/_sleep in the loop body) — a tight retry storm "
                "under failure",
            ))
        for call in server_calls:
            if not any(kw.arg in ("cancel", "cancels") for kw in call.keywords):
                findings.append(Finding(
                    path, call.lineno, "W019",
                    "server call re-issued in a retry loop without cancel=/"
                    "cancels= — the abandoned attempt can never be "
                    "cooperatively cancelled and burns device time to "
                    "completion",
                ))


_W021_SEGMENT_OPERAND = re.compile(
    r"codes|packed|values|nulls|mv_len|lengths|column|segment"
)
_W021_STAGING_SCOPE = re.compile(r"to_device|stage")


def _w021_ships_segment_operand(node: ast.AST) -> bool:
    """Any identifier in the shipped expression smells segment-sized."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _W021_SEGMENT_OPERAND.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and _W021_SEGMENT_OPERAND.search(sub.attr):
            return True
    return False


def _check_w021(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """W021: segment-sized `jax.device_put` outside the staging stream.

    Tiered storage (segment/residency.py) requires every segment-shaped
    host->device copy to run under a staging OWNER: charged against the
    residency budget (so eviction keeps HBM bounded) and issued on/overlapped
    with the copy stream.  A bare device_put of column arrays anywhere else
    on the serving path is an unbudgeted pin plus a synchronous PCIe stall.
    Functions whose name marks them as the staging path (`to_device`,
    `*stage*`) are exempt — they ARE the budgeted copy engine."""

    def visit(node: ast.AST, exempt: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_exempt = exempt
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_exempt = bool(_W021_STAGING_SCOPE.search(child.name))
            if isinstance(child, ast.Call) and not exempt:
                f = child.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "device_put"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "jax"
                    and child.args
                    and _w021_ships_segment_operand(child.args[0])
                ):
                    findings.append(Finding(
                        path, child.lineno, "W021",
                        "segment-sized jax.device_put outside the staging "
                        "stream — unbudgeted HBM pin and a synchronous PCIe "
                        "copy on the serving path; route it through "
                        "to_device/residency staging",
                    ))
            visit(child, child_exempt)

    visit(tree, False)


_W025_COLLECTIVES = frozenset(
    {"psum", "pmin", "pmax", "pmean", "all_gather", "all_to_all", "ppermute", "axis_index"}
)
_W025_AXIS_LITERALS = frozenset({"seg", "replica", "shard"})


def _w025_axis_literal(node: ast.AST) -> bool:
    """A bare axis-name literal: the string itself, or a tuple/list literal
    whose elements include one (the 2-D `("replica", "shard")` spelling)."""
    if isinstance(node, ast.Constant) and node.value in _W025_AXIS_LITERALS:
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(
            isinstance(e, ast.Constant) and e.value in _W025_AXIS_LITERALS for e in node.elts
        )
    return False


def _check_w025(path: str, tree: ast.AST, findings: List[Finding]) -> None:
    """W025: bare mesh-axis string literals at collective call sites.

    The 2-D (replica x shard) mesh made axis names a TOPOLOGY decision:
    engines carry the mesh's actual axes (parallel/mesh.data_axes) and
    combines must reduce hierarchically over them.  A collective called with
    a hardcoded "seg"/"replica"/"shard" literal silently binds the call site
    to one topology — it traces fine on the mesh it was written against and
    fails (or, worse, reduces over the wrong axis subset) on the others.
    parallel/mesh.py is exempt: it DEFINES the names."""
    norm = path.replace(os.sep, "/")
    if norm.endswith("parallel/mesh.py"):
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr in _W025_COLLECTIVES):
            continue
        # lax.psum / jax.lax.psum — anything else named psum is not a
        # mesh collective (e.g. a method on some other object)
        base = f.value
        is_lax = (isinstance(base, ast.Name) and base.id == "lax") or (
            isinstance(base, ast.Attribute)
            and base.attr == "lax"
            and isinstance(base.value, ast.Name)
            and base.value.id == "jax"
        )
        if not is_lax:
            continue
        operands = list(node.args) + [
            kw.value for kw in node.keywords if kw.arg in ("axis_name", "axis")
        ]
        for arg in operands:
            if _w025_axis_literal(arg):
                findings.append(Finding(
                    path, node.lineno, "W025",
                    f"collective lax.{f.attr} called with a bare mesh-axis "
                    "string literal — binds the call site to one mesh "
                    "topology; thread the engine's axis/axes (or the "
                    "parallel/mesh constants) instead",
                ))
                break


def lint_source(src: str, path: str = "<string>", threaded: bool = False) -> List[Finding]:
    """Lint one module's source.  `threaded` enables the cluster/-scoped
    rules (W004 shared-state races, W006 swallowed exceptions, W015
    unbounded serving-path growth, W018 blocking calls in async
    batch-dispatch paths)."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "E000", f"syntax error: {e.msg}")]

    jitted = _jitted_function_names(tree)
    pallas = _pallas_kernel_names(tree)
    kernel_rules = _KernelRules(path, findings)
    pallas_rules = _PallasKernelRules(path, findings)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and (node.name in jitted or _has_jit_decorator(node)):
            for stmt in node.body:
                kernel_rules.visit(stmt)
        if isinstance(node, ast.FunctionDef) and node.name in pallas:
            for stmt in node.body:
                pallas_rules.visit(stmt)
    _check_w003(path, tree, findings)
    _check_sync_in_loop(path, tree, findings)
    _check_w005(path, tree, findings)
    _check_w007(path, tree, findings)
    _check_w008(path, tree, findings)
    _check_w016(path, tree, findings)
    _check_w017(path, tree, findings)
    _check_w021(path, tree, findings)
    _check_w022(path, tree, findings)
    _check_w025(path, tree, findings)
    _check_w026(path, tree, findings)
    if threaded:
        _check_w004(path, tree, findings)
        _check_w006(path, tree, findings)
        _check_w015(path, tree, findings)
        _check_w018(path, tree, findings)
        _check_w019(path, tree, findings)
    suppressions = parse_suppressions(src)
    if suppressions:
        findings = [f for f in findings if not is_suppressed(f, suppressions)]
    return findings


def lint_paths(paths: Iterable[str], pkg_root: Optional[str] = None) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        rel = os.path.relpath(p, pkg_root) if pkg_root else p
        threaded = os.sep + "cluster" + os.sep in p or rel.startswith("cluster" + os.sep)
        with open(p, "r", encoding="utf-8") as f:
            findings.extend(lint_source(f.read(), path=rel, threaded=threaded))
    return findings


def lint_tree(root: Optional[str] = None) -> List[Finding]:
    """Lint every .py file under the pinot_tpu package (default: this one)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if name.endswith(".py"):
                paths.append(os.path.join(dirpath, name))
    return lint_paths(sorted(paths), pkg_root=os.path.dirname(root))
