"""Plan-time static type/shape checker over the query IR.

Walks Expr/Filter/QueryContext trees (query/ir.py) and validates — before
the planner traces anything into jax.jit — the invariants whose violation
otherwise surfaces as a tracer traceback deep inside XLA, or worse, as
silently-wrong results under TPU x32 integer wrapping:

  * function existence + arity against the transform/scalar/aggregation
    registries (query/transform.py, query/scalar.py, query/functions.py)
  * aggregation nesting (no agg inside an agg argument, GROUP BY or WHERE)
  * group-by key groupability (no literal keys)
  * predicate/column dtype compatibility, including int32-overflow and
    weak-type float promotion hazards against integer columns
  * LIMIT/OFFSET and aggregate ORDER BY sanity

Violations raise PlanCheckError (a ValueError) carrying a stable machine
code; cluster/rest.py maps it to a structured 400 response.  Checks are
deliberately conservative: only statically CERTAIN errors are flagged, so
every plan the executors accept today still passes.
"""
from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from pinot_tpu.query.ir import (
    AggregationSpec,
    Expr,
    ExprKind,
    FilterNode,
    FilterOp,
    Predicate,
    PredicateType,
    QueryContext,
    WindowSpec,
)

_INT32_MIN, _INT32_MAX = -(1 << 31), (1 << 31) - 1

# boolean/structural ops the parser emits inside CASE conditions and the
# funnel STEPS(...) form, plus engine-special select ops (UNNEST explodes in
# the executor, not the transform registry) — arity is validated elsewhere
_STRUCTURAL_OPS = frozenset(
    {"case", "steps", "unnest", "__and", "__or", "__not", "__eq", "__in", "__ge", "__gt", "__le", "__lt", "__isnull"}
)
_WINDOW_FNS = frozenset(
    {
        "row_number", "rank", "dense_rank", "ntile", "lag", "lead", "first_value",
        "last_value", "sum", "count", "avg", "min", "max", "bool_and", "bool_or",
    }
)


class PlanCheckError(ValueError):
    """One statically-detected plan defect, with a stable machine code."""

    def __init__(self, code: str, message: str, where: str = "query"):
        super().__init__(f"[{code}] {message} (in {where})")
        self.code = code
        self.detail = message
        self.where = where

    def to_dict(self) -> Dict[str, Any]:
        return {"errorCode": self.code, "error": self.detail, "where": self.where}


@dataclass(frozen=True)
class PlanIssue:
    code: str
    message: str
    where: str

    def to_error(self) -> PlanCheckError:
        return PlanCheckError(self.code, self.message, self.where)


# ---------------------------------------------------------------------------
# registry views (lazy: planner imports this module, transform imports scalar)
# ---------------------------------------------------------------------------
def _registries():
    from pinot_tpu.query import functions, scalar, transform

    return {
        "binary": set(transform._BINARY) | {"divide", "div"},
        "unary": set(transform._UNARY),
        "device": set(scalar.DEVICE_FNS),
        "device_multi": dict(scalar.DEVICE_MULTI_FNS),
        "dict": set(scalar.DICT_FNS),
        "agg": set(functions._REGISTRY),
    }


def _multi_fn_arity(fn) -> Tuple[int, Optional[int]]:
    """(min, max) positional arity of a DEVICE_MULTI_FNS entry; max=None for
    *args forms."""
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return 0, None
    lo = hi = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            hi += 1
            if p.default is p.empty:
                lo += 1
        elif p.kind is p.VAR_POSITIONAL:
            return lo, None
    return lo, hi


# ---------------------------------------------------------------------------
# expression walker
# ---------------------------------------------------------------------------
class _Checker:
    def __init__(self, ctx: QueryContext, schema=None):
        self.ctx = ctx
        self.schema = schema
        self.reg = _registries()
        self.issues: List[PlanIssue] = []
        self.aliases: Set[str] = {a for a in (ctx.select_aliases or []) if a}

    def issue(self, code: str, message: str, where: str) -> None:
        self.issues.append(PlanIssue(code, message, where))

    # -- columns ---------------------------------------------------------
    def check_column(self, name: str, where: str) -> None:
        if self.schema is None or name == "*" or name in self.aliases:
            return
        # internal/virtual names ($docId-style, join facades 'alias$col',
        # engine-injected '__'-prefixed helpers) bypass schema resolution
        if name.startswith(("$", "__")) or "$" in name or "." in name:
            return
        if name not in self.schema:
            self.issue(
                "UNKNOWN_COLUMN",
                f"column {name!r} is not in schema {self.schema.name!r}",
                where,
            )

    def _field(self, name: str):
        if self.schema is not None and name in self.schema:
            return self.schema.field(name)
        return None

    # -- expressions -----------------------------------------------------
    def check_expr(self, e: Optional[Expr], where: str, in_agg: bool = False, agg_ok: bool = True) -> None:
        """agg_ok: aggregation-named calls are legal here (select/order/having
        items resolve against reduced aggregation finals); in_agg: we are
        inside an aggregation argument, where a further agg call is nesting."""
        if e is None:
            return
        if e.kind is ExprKind.COLUMN:
            self.check_column(e.op, where)
            return
        if e.kind is ExprKind.LITERAL:
            return
        op = e.op
        reg = self.reg
        is_agg_name = op in reg["agg"]
        is_scalar_name = (
            op in reg["binary"] or op in reg["unary"] or op in reg["device"]
            or op in reg["device_multi"] or op in reg["dict"] or op in _STRUCTURAL_OPS
            or op in ("cast", "arraylength", "cardinality", "least", "greatest", "todatetime")
        )
        if is_agg_name and not is_scalar_name:
            if in_agg:
                self.issue(
                    "NESTED_AGGREGATION",
                    f"aggregation {op!r} cannot be nested inside another aggregation's arguments",
                    where,
                )
                return
            if not agg_ok:
                self.issue(
                    "NESTED_AGGREGATION",
                    f"aggregation {op!r} is not allowed here (WHERE / GROUP BY run before aggregation)",
                    where,
                )
                return
            # select/order/having position: the call resolves against a
            # reduced aggregation final; its argument is that agg's input
            for a in e.args:
                self.check_expr(a, where, in_agg=True, agg_ok=False)
            return
        # scalar calls pass agg-tolerance through: SUM(x)/COUNT(x) in a
        # select/order/having position is arithmetic over reduced finals
        child_agg_ok = agg_ok and not in_agg
        if not is_scalar_name:
            self.issue("UNKNOWN_FUNCTION", f"unknown function {op!r}", where)
            # still walk args: one bad call should not mask a second defect
            for a in e.args:
                self.check_expr(a, where, in_agg=in_agg, agg_ok=child_agg_ok)
            return
        self._check_arity(e, where)
        for a in e.args:
            self.check_expr(a, where, in_agg=in_agg, agg_ok=child_agg_ok)

    def _check_arity(self, e: Expr, where: str) -> None:
        op, n = e.op, len(e.args)
        reg = self.reg
        if op in reg["binary"] and n != 2:
            self.issue("BAD_ARITY", f"{op}() takes exactly 2 arguments, got {n}", where)
        elif op in reg["unary"] and n != 1:
            self.issue("BAD_ARITY", f"{op}() takes exactly 1 argument, got {n}", where)
        elif op == "cast" and (n != 2 or not e.args[1].is_literal):
            self.issue("BAD_ARITY", "cast() takes (expression, type-literal)", where)
        elif op in ("arraylength", "cardinality") and n != 1:
            self.issue("BAD_ARITY", f"{op}() takes exactly 1 argument, got {n}", where)
        elif op in ("least", "greatest") and n < 1:
            self.issue("BAD_ARITY", f"{op}() needs at least 1 argument", where)
        elif op in reg["device_multi"]:
            lo, hi = _multi_fn_arity(reg["device_multi"][op])
            if n < lo or (hi is not None and n > hi):
                want = f"{lo}" if hi == lo else f"{lo}..{'*' if hi is None else hi}"
                self.issue("BAD_ARITY", f"{op}() takes {want} arguments, got {n}", where)
        elif op in reg["device"] or op in reg["dict"]:
            # one traced operand + literal parameters (transform.py contract)
            traced = [a for a in e.args if not a.is_literal]
            if len(traced) != 1:
                self.issue(
                    "BAD_ARITY",
                    f"{op}() expects exactly one column/expression argument, got {len(traced)}",
                    where,
                )

    # -- filters ---------------------------------------------------------
    def check_filter(self, node: Optional[FilterNode], where: str, agg_ok: bool = False) -> None:
        if node is None:
            return
        if node.op is FilterOp.PRED and node.predicate is not None:
            self.check_predicate(node.predicate, where, agg_ok=agg_ok)
            return
        for c in node.children:
            self.check_filter(c, where, agg_ok=agg_ok)

    def check_predicate(self, p: Predicate, where: str, agg_ok: bool = False) -> None:
        self.check_expr(p.lhs, where, agg_ok=agg_ok)
        if not p.lhs.is_column:
            return
        f = self._field(p.lhs.op)
        if f is None:
            return
        dt = f.data_type
        values: List[Any] = []
        if p.ptype in (PredicateType.EQ, PredicateType.NEQ, PredicateType.IN, PredicateType.NOT_IN):
            values = list(p.values)
        elif p.ptype is PredicateType.RANGE:
            values = [v for v in (p.lower, p.upper) if v is not None]
        if dt.is_numeric and not dt.name == "BOOLEAN":
            for v in values:
                if isinstance(v, str):
                    try:
                        float(v)
                    except (TypeError, ValueError):
                        self.issue(
                            "TYPE_MISMATCH",
                            f"non-numeric literal {v!r} compared against {dt.name} column {p.lhs.op!r}",
                            where,
                        )
                elif isinstance(v, bool):
                    continue
                elif isinstance(v, int) and dt.name == "INT" and not _INT32_MIN <= v <= _INT32_MAX:
                    self.issue(
                        "INT32_OVERFLOW",
                        f"literal {v} overflows INT column {p.lhs.op!r} (int32 wraps under TPU x32)",
                        where,
                    )
                elif (
                    isinstance(v, float)
                    and dt.name in ("INT", "LONG", "TIMESTAMP")
                    and v != int(v)
                    and p.ptype in (PredicateType.EQ, PredicateType.IN)
                ):
                    self.issue(
                        "WEAK_TYPE_PROMOTION",
                        f"equality on {dt.name} column {p.lhs.op!r} against non-integral float "
                        f"{v!r} can never match (weak f32 promotion hazard in kernels)",
                        where,
                    )
        if p.ptype in (PredicateType.REGEXP_LIKE, PredicateType.LIKE, PredicateType.TEXT_MATCH) and not dt.is_string_like:
            self.issue(
                "TYPE_MISMATCH",
                f"{p.ptype.value} requires a string-like column, {p.lhs.op!r} is {dt.name}",
                where,
            )

    # -- aggregations ----------------------------------------------------
    def check_aggregation(self, spec: AggregationSpec, where: str) -> None:
        from pinot_tpu.query import functions

        if spec.function not in self.reg["agg"]:
            self.issue("UNKNOWN_AGGREGATION", f"unknown aggregation function {spec.function!r}", where)
            return
        try:
            fn = functions.for_spec(spec)
        except (ValueError, TypeError) as exc:
            self.issue("BAD_ARITY", f"{spec.function}: {exc}", where)
            fn = None
        if fn is not None and getattr(fn, "needs_expr", True) and spec.expr is None:
            self.issue("BAD_ARITY", f"{spec.function}() requires an argument expression", where)
        self.check_expr(spec.expr, where, in_agg=True, agg_ok=False)
        for ex in spec.extra_exprs:
            self.check_expr(ex, where, in_agg=True, agg_ok=False)
        self.check_filter(spec.filter, f"{where} FILTER", agg_ok=False)

    def check_window(self, spec: WindowSpec, where: str) -> None:
        if spec.function not in _WINDOW_FNS:
            self.issue("UNKNOWN_FUNCTION", f"unknown window function {spec.function!r}", where)
        self.check_expr(spec.expr, where, in_agg=True, agg_ok=False)
        for p in spec.partition_by:
            self.check_expr(p, where, agg_ok=False)
        for o in spec.order_by:
            self.check_expr(o.expr, where, agg_ok=False)

    # -- whole context ---------------------------------------------------
    def run(self) -> List[PlanIssue]:
        ctx = self.ctx
        if ctx.limit is not None and ctx.limit < 0:
            self.issue("BAD_LIMIT", f"LIMIT must be >= 0, got {ctx.limit}", "LIMIT")
        if ctx.offset is not None and ctx.offset < 0:
            self.issue("BAD_LIMIT", f"OFFSET must be >= 0, got {ctx.offset}", "OFFSET")

        for i, s in enumerate(ctx.select_list):
            where = f"select item {i + 1}"
            if isinstance(s, AggregationSpec):
                self.check_aggregation(s, where)
            elif isinstance(s, WindowSpec):
                self.check_window(s, where)
            else:
                self.check_expr(s, where, agg_ok=True)
        for spec in ctx.extra_aggregations:
            self.check_aggregation(spec, "extra aggregation")

        self.check_filter(ctx.filter, "WHERE", agg_ok=False)

        group_fps = set()
        for i, g in enumerate(ctx.group_by):
            where = f"GROUP BY key {i + 1}"
            group_fps.add(g.fingerprint())
            if g.is_literal:
                self.issue("UNGROUPABLE_KEY", f"cannot group by literal {g.value!r}", where)
                continue
            self.check_expr(g, where, agg_ok=False)

        self.check_filter(ctx.having, "HAVING", agg_ok=True)

        group_cols = {g.op for g in ctx.group_by if g.is_column}
        for i, ob in enumerate(ctx.order_by):
            where = f"ORDER BY item {i + 1}"
            self.check_expr(ob.expr, where, agg_ok=True)
            if (
                ctx.is_aggregate
                and ob.expr.is_column
                and ob.expr.op not in group_cols
                and ob.expr.op not in self.aliases
                and ob.expr.fingerprint() not in group_fps
                and ob.expr.op != "*"
            ):
                self.issue(
                    "BAD_ORDER_BY",
                    f"ORDER BY column {ob.expr.op!r} is neither a GROUP BY key nor a select alias "
                    "in an aggregate query",
                    where,
                )
        return self.issues


def collect_issues(ctx: QueryContext, schema=None) -> List[PlanIssue]:
    """All statically-detected defects of one plan (empty = plan is clean)."""
    return _Checker(ctx, schema).run()


def check_plan(ctx: QueryContext, schema=None) -> None:
    """Raise PlanCheckError for the first defect; no-op on clean plans."""
    issues = collect_issues(ctx, schema)
    if issues:
        raise issues[0].to_error()


# planner-path memo: plan_segment runs per segment, the ctx check is
# per-fingerprint — remember clean fingerprints so the per-segment cost is
# one dict hit (bounded; malformed plans never enter, they raise)
_CHECKED_FPS: Dict[str, bool] = {}
_CHECKED_CAP = 4096


def check_plan_cached(ctx: QueryContext, schema=None) -> None:
    fp = ctx.fingerprint()
    if fp in _CHECKED_FPS:
        return
    check_plan(ctx, schema)
    if len(_CHECKED_FPS) >= _CHECKED_CAP:
        _CHECKED_FPS.clear()
    _CHECKED_FPS[fp] = True
