"""Exploration harness over the deterministic scheduler.

`explore()` drives a protocol MODEL (analysis/models.py) through a budget
of schedules — alternating unbounded seeded-random schedules with
preemption-bounded ones (the CHESS observation: most concurrency bugs
need ≤2 preemptions, so bounded schedules concentrate the budget where
bugs live).  Each schedule runs the model's threads to quiescence under a
`SchedulerProvider`, checking

  * the model's ALWAYS-invariants after every scheduled step,
  * its QUIESCENCE-invariants once every thread has finished,
  * thread crashes (an uncaught exception in any model thread),
  * deadlocks and livelocks (raised by the scheduler itself).

A failing schedule serializes to a JSON-able TRACE — the protocol name,
mutation, seed, preemption bound, the exact sequence of task ids the
scheduler chose, and the failure (kind, detail, step index).  `replay()`
re-runs the trace with the schedule FORCED, reproducing the identical
failure at the identical step: the debugging loop is "capture once,
replay forever".

The model contract (duck-typed; see models.py):

    model = ModelCls(mutation=None_or_name)
    model.setup()            # construct protocol objects under the provider
    model.threads()          # [(name, zero-arg fn), ...] — fixed order
    model.invariants()       # [(name, fn->None|str)], checked every step
    model.at_quiescence()    # [(name, fn->None|str)], checked at the end
    model.teardown()         # cleanup (tmpdirs etc.)

Invariant callbacks run on the HARNESS thread between steps, while every
model thread is parked at a yield point — they must read protocol state
raw (plain attributes) and never touch a provider primitive.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple, Type

from pinot_tpu.analysis.scheduler import (
    DeadlockError,
    DeterministicScheduler,
    LivelockError,
    SchedulerProvider,
    TraceDivergenceError,
)
from pinot_tpu.utils import threads


class InvariantViolation(AssertionError):
    def __init__(self, name: str, detail: str):
        super().__init__(f"{name}: {detail}")
        self.invariant = name
        self.detail = detail


def _failure(kind: str, detail: str, sched: DeterministicScheduler) -> Dict[str, Any]:
    return {
        "kind": kind,
        "detail": detail,
        "step": len(sched.trace),
        "schedule": list(sched.trace),
    }


def run_schedule(
    model_cls: Type,
    seed: int = 0,
    preemption_bound: Optional[int] = None,
    schedule: Optional[List[int]] = None,
    mutation: Optional[str] = None,
    max_steps: int = 20_000,
) -> Optional[Dict[str, Any]]:
    """One schedule of one model.  Returns a failure record, or None when
    the schedule ran to quiescence with every invariant holding."""
    sched = DeterministicScheduler(
        seed=seed,
        preemption_bound=preemption_bound,
        schedule=schedule,
        max_steps=max_steps,
    )
    prov = SchedulerProvider(sched)
    model = model_cls(mutation=mutation)
    failure: Optional[Dict[str, Any]] = None
    with threads.use_provider(prov), prov:
        try:
            model.setup()
            for tname, fn in model.threads():
                threads.Thread(target=fn, name=tname).start()
            always = model.invariants()

            def on_step() -> None:
                for iname, check in always:
                    msg = check()
                    if msg:
                        raise InvariantViolation(iname, str(msg))

            sched.on_step = on_step
            try:
                sched.run()
                for t in sched.tasks:
                    if t.exc is not None:
                        failure = _failure(
                            "thread-crash", f"{t.name}: {t.exc!r}", sched
                        )
                        break
                if failure is None:
                    for iname, check in model.at_quiescence():
                        msg = check()
                        if msg:
                            failure = _failure("quiescence", f"{iname}: {msg}", sched)
                            break
            except InvariantViolation as e:
                failure = _failure("invariant", str(e), sched)
            except DeadlockError as e:
                failure = _failure("deadlock", str(e), sched)
            except LivelockError as e:
                failure = _failure("livelock", str(e), sched)
        finally:
            sched.shutdown()
            try:
                model.teardown()
            except Exception:  # noqa: BLE001 — teardown must not mask the failure
                pass
    if failure is not None:
        failure["seed"] = seed
        failure["preemptionBound"] = preemption_bound
    return failure


def explore(
    model_cls: Type,
    max_schedules: int = 40,
    seed: int = 0,
    mutation: Optional[str] = None,
    preemption_bound: int = 2,
) -> Dict[str, Any]:
    """Drive `max_schedules` schedules (even index: unbounded random; odd:
    preemption-bounded) and stop at the first failure.  The returned record
    carries everything `replay()` needs."""
    for i in range(max_schedules):
        pb = None if i % 2 == 0 else preemption_bound
        failure = run_schedule(
            model_cls, seed=seed + i, preemption_bound=pb, mutation=mutation
        )
        if failure is not None:
            return {
                "protocol": getattr(model_cls, "name", model_cls.__name__),
                "mutation": mutation,
                "schedulesExplored": i + 1,
                "failure": failure,
            }
    return {
        "protocol": getattr(model_cls, "name", model_cls.__name__),
        "mutation": mutation,
        "schedulesExplored": max_schedules,
        "failure": None,
    }


def replay(trace: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Re-run a captured failing trace with the schedule FORCED.  Returns
    the reproduced failure record (bit-identical kind/detail/step/schedule
    for a faithful trace); raises TraceDivergenceError when the code under
    test no longer matches the trace."""
    from pinot_tpu.analysis.models import PROTOCOLS

    model_cls = PROTOCOLS[trace["protocol"]]
    failure = trace["failure"]
    return run_schedule(
        model_cls,
        seed=failure.get("seed", 0),
        preemption_bound=failure.get("preemptionBound"),
        schedule=list(failure["schedule"]),
        mutation=trace.get("mutation"),
    )


def save_trace(trace: Dict[str, Any], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f, indent=2, sort_keys=True)


def load_trace(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def check_all(
    seed: int = 0,
    max_schedules: int = 25,
    mutations: bool = False,
    protocols: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """The gate entry point: every registered protocol model explored over
    the seeded budget; with `mutations=True` every broken twin must FAIL
    within the same budget (mutation-detection coverage).  `ok` is the
    single gate bit: clean models clean, broken twins caught."""
    from pinot_tpu.analysis.models import PROTOCOLS

    names = protocols if protocols is not None else sorted(PROTOCOLS)
    report: Dict[str, Any] = {"seed": seed, "maxSchedules": max_schedules, "protocols": {}}
    ok = True
    for name in names:
        model_cls = PROTOCOLS[name]
        clean = explore(model_cls, max_schedules=max_schedules, seed=seed)
        entry: Dict[str, Any] = {
            "schedulesExplored": clean["schedulesExplored"],
            "failure": clean["failure"],
            "invariants": [iname for iname, _ in _invariant_names(model_cls)],
        }
        if clean["failure"] is not None:
            ok = False
        if mutations:
            entry["mutations"] = {}
            for mut in getattr(model_cls, "MUTATIONS", ()):  # broken twins
                res = explore(model_cls, max_schedules=max_schedules, seed=seed, mutation=mut)
                caught = res["failure"] is not None
                entry["mutations"][mut] = {
                    "caught": caught,
                    "schedulesExplored": res["schedulesExplored"],
                    "failure": res["failure"],
                }
                if not caught:
                    ok = False
        report["protocols"][name] = entry
    report["ok"] = ok
    return report


def _invariant_names(model_cls: Type) -> List[Tuple[str, Any]]:
    """Invariant (name, fn) pairs without running a schedule — a throwaway
    instance is set up under the REAL provider just to enumerate names."""
    try:
        m = model_cls(mutation=None)
        m.setup()
        pairs = list(m.invariants()) + list(m.at_quiescence())
        try:
            m.teardown()
        except Exception:  # noqa: BLE001
            pass
        return pairs
    except Exception:  # noqa: BLE001 — observability only, never gate on it
        return []
