"""Interprocedural analysis engine: whole-package project model + pass API.

The per-file lint (repo_lint.py) sees one module at a time; the race
detector (races.py) and the host-device sync auditor (device_sync.py)
need the whole package — which class a method belongs to, what a call
resolves to, what a function returns.  This module builds that model:

  Project
    .modules    {dotted module name -> ModuleInfo (ast, imports, suppressions)}
    .functions  {qualified name -> FunctionInfo (top-level defs + methods)}
    .classes    {qualified name -> ClassInfo (methods, bases, lock attrs live
                 in races.py — the engine stays policy-free)}
    .resolve_call(fn_info, call_node) -> dotted target ("pinot_tpu.x.C.m",
                 "time.sleep", "jax.numpy.sum") or None when unresolvable

Passes subclass `Pass` and implement run(project) -> [Finding].  The
runner (run_project) applies three filters before findings count:

  * inline `# pinot-lint: disable=W0xx` suppressions (same syntax the
    per-file rules honor),
  * the committed baseline (analysis/baseline.json): triaged pre-existing
    findings matched by (rule, path, symbol-or-line) with a one-line
    justification each — stale entries (matching nothing) are reported so
    the baseline can only shrink,
  * nothing else: anything left is a hard `cli lint` failure.
"""
from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pinot_tpu.analysis.repo_lint import (
    Finding,
    is_suppressed,
    lint_source,
    parse_suppressions,
)

_THREADED_HINT_DIRS = ("cluster",)  # per-file threaded scope, as lint_paths


@dataclass
class ModuleInfo:
    relpath: str            # e.g. "pinot_tpu/cluster/broker.py"
    name: str               # e.g. "pinot_tpu.cluster.broker"
    tree: ast.Module
    source: str
    imports: Dict[str, str] = field(default_factory=dict)   # alias -> dotted
    suppressions: Dict[int, Optional[Set[str]]] = field(default_factory=dict)
    threaded: bool = False  # imports threading (directly)


@dataclass
class ClassInfo:
    qname: str              # "pinot_tpu.cluster.broker.Broker"
    name: str
    module: ModuleInfo
    node: ast.ClassDef
    methods: Dict[str, "FunctionInfo"] = field(default_factory=dict)
    base_names: List[str] = field(default_factory=list)     # raw source names


@dataclass
class FunctionInfo:
    qname: str              # "...broker.Broker.route" or "...engine.run"
    name: str
    module: ModuleInfo
    node: ast.FunctionDef
    cls: Optional[ClassInfo] = None


def _module_imports(tree: ast.Module) -> Dict[str, str]:
    """Flat alias->dotted-name map, including function-local imports (the
    repo routinely does `import jax` inside functions to keep cold paths
    import-light)."""
    imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    # `import jax.numpy` binds `jax`; map the root name
                    root = a.name.split(".", 1)[0]
                    imports.setdefault(root, root)
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                imports[a.asname or a.name] = f"{node.module}.{a.name}"
    return imports


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


class Project:
    """Symbol tables + call resolution over one package tree (or an
    in-memory fixture package — see from_sources, used by the tests)."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}

    # -- construction -----------------------------------------------------

    @classmethod
    def from_tree(cls, root: Optional[str] = None) -> "Project":
        """Build from a package directory (default: the installed pinot_tpu
        package, like repo_lint.lint_tree)."""
        if root is None:
            root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pkg_parent = os.path.dirname(root)
        sources: Dict[str, str] = {}
        for dirpath, _dirnames, filenames in os.walk(root):
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, pkg_parent)
                with open(full, "r", encoding="utf-8") as f:
                    sources[rel] = f.read()
        return cls.from_sources(sources)

    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "Project":
        """Build from {relpath: source}.  relpaths use '/' separators and
        include the package directory ("pkg/cluster/broker.py")."""
        proj = cls()
        for relpath in sorted(sources):
            src = sources[relpath]
            norm = relpath.replace(os.sep, "/")
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue  # per-file lint reports E000; the model skips it
            modname = norm[:-3].replace("/", ".")
            if modname.endswith(".__init__"):
                modname = modname[: -len(".__init__")]
            mi = ModuleInfo(
                relpath=norm,
                name=modname,
                tree=tree,
                source=src,
                imports=_module_imports(tree),
                suppressions=parse_suppressions(src),
                threaded=any(
                    v == "threading"
                    or v.startswith("threading.")
                    or v == "pinot_tpu.utils.threads"
                    or v.startswith("pinot_tpu.utils.threads.")
                    for v in _module_imports(tree).values()
                ),
            )
            proj.modules[modname] = mi
            proj._index_module(mi)
        return proj

    def _index_module(self, mi: ModuleInfo) -> None:
        for node in mi.tree.body:
            if isinstance(node, _FUNC_NODES):
                qn = f"{mi.name}.{node.name}"
                self.functions[qn] = FunctionInfo(qn, node.name, mi, node)
            elif isinstance(node, ast.ClassDef):
                cq = f"{mi.name}.{node.name}"
                ci = ClassInfo(cq, node.name, mi, node)
                for base in node.bases:
                    if isinstance(base, ast.Name):
                        ci.base_names.append(base.id)
                    elif isinstance(base, ast.Attribute):
                        ci.base_names.append(base.attr)
                for sub in node.body:
                    if isinstance(sub, _FUNC_NODES):
                        fq = f"{cq}.{sub.name}"
                        fi = FunctionInfo(fq, sub.name, mi, sub, cls=ci)
                        ci.methods[sub.name] = fi
                        self.functions[fq] = fi
                self.classes[cq] = ci

    # -- resolution -------------------------------------------------------

    def resolve_name(self, mi: ModuleInfo, name: str) -> Optional[str]:
        """A bare Name in module `mi` -> dotted target (project symbol,
        project module, or external dotted name via imports)."""
        local = f"{mi.name}.{name}"
        if local in self.functions or local in self.classes:
            return local
        return mi.imports.get(name)

    def _base_method(self, ci: ClassInfo, attr: str) -> Optional[str]:
        """Look up `attr` on ci's bases (single level, by source name —
        enough for the repo's shallow hierarchies)."""
        for bname in ci.base_names:
            target = self.resolve_name(ci.module, bname)
            base = self.classes.get(target or "")
            if base is None:
                continue
            if attr in base.methods:
                return base.methods[attr].qname
            deeper = self._base_method(base, attr)
            if deeper:
                return deeper
        return None

    def resolve_call(self, fi: FunctionInfo, call: ast.Call) -> Optional[str]:
        """Resolve a Call node to a dotted name.  Project symbols resolve
        to their qualified name ("pkg.mod.Class.method"); known imports
        resolve to external dotted names ("time.sleep", "jax.numpy.sum");
        everything else (locals, unknown object attributes) returns None."""
        return self.resolve_expr(fi, call.func)

    def resolve_expr(self, fi: FunctionInfo, f: ast.AST) -> Optional[str]:
        mi = fi.module
        if isinstance(f, ast.Name):
            return self.resolve_name(mi, f.id)
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name):
                if base.id == "self" and fi.cls is not None:
                    if f.attr in fi.cls.methods:
                        return fi.cls.methods[f.attr].qname
                    inherited = self._base_method(fi.cls, f.attr)
                    if inherited:
                        return inherited
                    return None  # self.<data attr>(...) — not a method
                root = self.resolve_name(mi, base.id)
                if root is not None:
                    return f"{root}.{f.attr}"
                return None
            if isinstance(base, ast.Attribute):
                inner = self.resolve_expr(fi, base)
                if inner is not None:
                    return f"{inner}.{f.attr}"
        return None

    def class_of(self, qname: str) -> Optional[ClassInfo]:
        fi = self.functions.get(qname)
        return fi.cls if fi else None


# -- pass API -------------------------------------------------------------


class Pass:
    """One interprocedural rule family.  Subclasses set `name` and
    implement run()."""

    name = "pass"

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def default_passes() -> List[Pass]:
    from pinot_tpu.analysis.device_sync import DeviceSyncPass
    from pinot_tpu.analysis.lifecycle import ConditionDisciplinePass, LifecyclePass
    from pinot_tpu.analysis.races import RacePass

    return [RacePass(), DeviceSyncPass(), LifecyclePass(), ConditionDisciplinePass()]


# -- baseline -------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load_baseline(path: Optional[str] = None) -> List[Dict[str, object]]:
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("suppressions", []))


def _baseline_matches(entry: Dict[str, object], f: Finding) -> bool:
    if entry.get("rule") != f.rule:
        return False
    if not str(f.path).endswith(str(entry.get("path", ""))):
        return False
    # symbol match is preferred (robust to line drift); line is the fallback
    sym = entry.get("symbol")
    if sym:
        return sym == f.symbol
    return entry.get("line") == f.line


def apply_baseline(
    findings: List[Finding], baseline: List[Dict[str, object]]
) -> Tuple[List[Finding], int, List[Dict[str, object]]]:
    """Returns (kept findings, #baselined, stale entries that matched
    nothing — a stale baseline means the bug was fixed: delete the entry)."""
    used = [False] * len(baseline)
    kept: List[Finding] = []
    baselined = 0
    for f in findings:
        hit = False
        for i, entry in enumerate(baseline):
            if _baseline_matches(entry, f):
                used[i] = True
                hit = True
        if hit:
            baselined += 1
        else:
            kept.append(f)
    stale = [e for i, e in enumerate(baseline) if not used[i]]
    return kept, baselined, stale


# -- runner ---------------------------------------------------------------


@dataclass
class AnalysisReport:
    findings: List[Finding]
    baselined: int = 0
    stale_baseline: List[Dict[str, object]] = field(default_factory=list)
    per_file_count: int = 0
    interprocedural_count: int = 0


def run_passes(project: Project, passes: Optional[Iterable[Pass]] = None) -> List[Finding]:
    """Run interprocedural passes only (no per-file lint, no baseline) —
    the raw-findings entry point the fixture tests use."""
    out: List[Finding] = []
    for p in passes if passes is not None else default_passes():
        out.extend(p.run(project))
    # inline suppressions are honored even on the raw path
    by_rel = {mi.relpath: mi for mi in project.modules.values()}
    kept = []
    for f in out:
        mi = by_rel.get(f.path)
        if mi is not None and is_suppressed(f, mi.suppressions):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept


def run_project(
    root: Optional[str] = None,
    passes: Optional[Iterable[Pass]] = None,
    baseline_path: Optional[str] = None,
) -> AnalysisReport:
    """Full `cli lint` pipeline: per-file rules + interprocedural passes,
    inline suppressions, then the committed baseline."""
    project = Project.from_tree(root)
    per_file: List[Finding] = []
    for mi in project.modules.values():
        threaded = any(f"/{d}/" in f"/{mi.relpath}" for d in _THREADED_HINT_DIRS)
        per_file.extend(lint_source(mi.source, path=mi.relpath, threaded=threaded))
    inter = run_passes(project, passes)
    findings = sorted(per_file + inter, key=lambda f: (f.path, f.line, f.rule))
    baseline = load_baseline(baseline_path)
    findings, baselined, stale = apply_baseline(findings, baseline)
    return AnalysisReport(
        findings=findings,
        baselined=baselined,
        stale_baseline=stale,
        per_file_count=len(per_file),
        interprocedural_count=len(inter),
    )
