"""Recompilation guard: fingerprint -> compile-event audit for kernel caches.

Every engine caches compiled kernels by (query fingerprint, layout
signature).  A cache whose signature churns — segments with drifting
shapes, per-query closure constants leaking into the key — recompiles the
same query shape over and over; on TPU each recompile costs seconds and
the 2e9 rows/s hot path degrades to tracing.  The audit records one event
per cache miss, exports counters through utils.metrics, and flags the
same fingerprint compiling more than `threshold` times: warn by default,
raise RecompilationStormError when PINOT_TPU_RECOMPILE_STRICT=1.
"""
from __future__ import annotations

import os
import threading
import warnings
from typing import Any, Dict, Optional

from pinot_tpu.utils.metrics import METRICS

_DEFAULT_THRESHOLD = 32  # distinct segment layouts per query shape is legit; storms are 100s


class RecompilationStormError(RuntimeError):
    """Same query fingerprint recompiled more than the audit threshold."""


class CompileAudit:
    """Per-cache compile/hit recorder (one instance per kernel cache)."""

    def __init__(self, name: str, threshold: Optional[int] = None, strict: Optional[bool] = None):
        self.name = name
        self.threshold = (
            threshold
            if threshold is not None
            else int(os.environ.get("PINOT_TPU_RECOMPILE_LIMIT", _DEFAULT_THRESHOLD))
        )
        self.strict = (
            strict
            if strict is not None
            else os.environ.get("PINOT_TPU_RECOMPILE_STRICT", "0") not in ("0", "", "false")
        )
        self._lock = threading.Lock()
        self._compiles: Dict[str, int] = {}
        self._hits = 0

    def record_compile(self, fingerprint: str) -> None:
        """Record one cache-miss compile of `fingerprint` (call at jit time)."""
        with self._lock:
            n = self._compiles.get(fingerprint, 0) + 1
            self._compiles[fingerprint] = n
        METRICS.counter(f"compile.{self.name}.compiles").inc()
        if n > self.threshold:
            msg = (
                f"query shape recompiled {n}x in cache {self.name!r} "
                f"(threshold {self.threshold}): likely a recompilation storm — "
                f"per-segment constants leaking into the plan key? fp={fingerprint[:80]}"
            )
            METRICS.counter(f"compile.{self.name}.storms").inc()
            if self.strict:
                raise RecompilationStormError(msg)
            warnings.warn(msg, RuntimeWarning, stacklevel=3)

    def record_hit(self, fingerprint: str) -> None:
        with self._lock:
            self._hits += 1
        METRICS.counter(f"compile.{self.name}.hits").inc()

    def compile_count(self, fingerprint: str) -> int:
        with self._lock:
            return self._compiles.get(fingerprint, 0)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._compiles)

    def hit_count(self) -> int:
        with self._lock:
            return self._hits

    def summary(self) -> Dict[str, Any]:
        """Plan-cache effectiveness snapshot since the last reset():
        cold_compiles = distinct shapes traced for the first time,
        warm_recompiles = re-traces of an already-seen shape (structure
        mismatch or cache eviction — the expensive kind a literal leak
        causes), hits = warm-path cache hits, hit_rate over all lookups."""
        with self._lock:
            total = sum(self._compiles.values())
            cold = len(self._compiles)
            hits = self._hits
        lookups = hits + total
        return {
            "hits": hits,
            "compiles_total": total,
            "cold_compiles": cold,
            "warm_recompiles": total - cold,
            "hit_rate": (hits / lookups) if lookups else 0.0,
        }

    def reset(self) -> None:
        with self._lock:
            self._compiles.clear()
            self._hits = 0


# one audit per kernel cache: the SSE per-segment plan cache
# (query/planner.py), the distributed-combine cache (parallel/engine.py)
# and the multi-stage join cache (mse/engine.py)
SSE_AUDIT = CompileAudit("sse")
DIST_AUDIT = CompileAudit("dist")
MSE_AUDIT = CompileAudit("mse")


def reset_all() -> None:
    for a in (SSE_AUDIT, DIST_AUDIT, MSE_AUDIT):
        a.reset()
