"""Bloom filter for host-side segment pruning on equality predicates.

Reference parity: BloomFilterReader + BloomFilterSegmentPruner
(pinot-core/.../core/query/pruner/BloomFilterSegmentPruner.java).  Pruning is
host-side work done BEFORE any kernel launch, so this is plain numpy — no
device involvement.  Dict-encoded columns rarely need it (the sorted
dictionary answers membership exactly); it earns its keep on raw (no-dict)
columns where membership would otherwise need a scan.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from pinot_tpu.utils.hashing import hash2_64 as _hash2


class BloomFilter:
    KIND = "bloom"

    def __init__(self, bits: np.ndarray, num_hashes: int):
        self.bits = bits  # uint64 words
        self.num_hashes = num_hashes

    @property
    def num_bits(self) -> int:
        return len(self.bits) * 64

    @staticmethod
    def build(values, fpp: float = 0.03) -> "BloomFilter":
        values = list(values)
        n = max(1, len(values))
        m = int(-n * np.log(fpp) / (np.log(2) ** 2))
        m = max(64, (m + 63) // 64 * 64)
        k = max(1, round(m / n * np.log(2)))
        bf = BloomFilter(np.zeros(m // 64, dtype=np.uint64), k)
        for v in values:
            bf._add(v)
        return bf

    def _positions(self, value):
        h1, h2 = _hash2(value)
        m = self.num_bits
        return [(int(h1) + i * int(h2)) % m for i in range(self.num_hashes)]

    def _add(self, value) -> None:
        for p in self._positions(value):
            self.bits[p >> 6] |= np.uint64(1 << (p & 63))

    def might_contain(self, value) -> bool:
        return all(self.bits[p >> 6] & np.uint64(1 << (p & 63)) for p in self._positions(value))

    def to_regions(self, prefix: str):
        yield f"{prefix}.bits", self.bits

    def meta(self) -> Dict[str, Any]:
        return {"numHashes": self.num_hashes}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "BloomFilter":
        return BloomFilter(np.asarray(regions[f"{prefix}.bits"]), meta["numHashes"])
