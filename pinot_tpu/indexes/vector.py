"""Vector index: HBM-resident normalized embedding matrix, brute-force top-k.

Reference parity: Pinot's Lucene-HNSW vector index + VECTOR_SIMILARITY
predicate (pinot-core/.../operator/filter/VectorSimilarityFilterOperator.java).

Re-design (SURVEY.md §2.4: "vector ANN: TPU brute-force/IVF matmul scan is
idiomatic"): no graph structure — the index IS a row-normalized [n, d]
float32 matrix pinned in HBM.  VECTOR_SIMILARITY(col, q, k) becomes one
matvec on the MXU + jax.lax.top_k, exact (recall 1.0, which HNSW cannot
claim) and fast up to tens of millions of rows per chip.  Cosine similarity
via pre-normalized rows; zero-length/padded rows get -inf score."""
from __future__ import annotations

import json
from typing import Any, Dict

import numpy as np


class VectorIndex:
    KIND = "vector"

    def __init__(self, matrix: np.ndarray, dim: int):
        self.matrix = matrix  # [n, d] float32, rows L2-normalized (0 rows stay 0)
        self.dim = dim

    @staticmethod
    def build(values: np.ndarray, lengths: np.ndarray) -> "VectorIndex":
        """values: padded [n, max_len] float matrix; rows with length != the
        modal dimension are zeroed (score -inf at query time)."""
        m = np.asarray(values, dtype=np.float32)
        dims = np.bincount(lengths[lengths > 0]) if len(lengths) else np.array([1])
        dim = int(np.argmax(dims)) if dims.size else m.shape[1]
        ok = lengths == dim
        m = np.where(ok[:, None], m, 0.0)[:, :dim]
        norms = np.linalg.norm(m, axis=1, keepdims=True)
        norms[norms == 0] = 1.0
        return VectorIndex((m / norms).astype(np.float32), dim)

    def normalize_query(self, q) -> np.ndarray:
        q = np.asarray(q, dtype=np.float32).reshape(-1)
        if len(q) != self.dim:
            raise ValueError(f"query vector dim {len(q)} != index dim {self.dim}")
        n = np.linalg.norm(q)
        return q / (n if n else 1.0)

    # -- persistence -------------------------------------------------------
    def to_regions(self, prefix: str):
        return [(f"{prefix}.mat", self.matrix)]

    def meta(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "dim": self.dim}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "VectorIndex":
        return VectorIndex(np.asarray(regions[f"{prefix}.mat"]), meta["dim"])


def parse_query_vector(raw) -> np.ndarray:
    """VECTOR_SIMILARITY's query argument: a JSON-array string or sequence."""
    if isinstance(raw, str):
        return np.asarray(json.loads(raw), dtype=np.float32)
    return np.asarray(raw, dtype=np.float32)
