"""Pluggable index registry (StandardIndexes analog,
pinot-segment-spi/.../spi/index/StandardIndexes.java:73-157).

Each index kind implements: build(...), to_regions(prefix), meta(),
from_regions(meta, regions, prefix); segments persist them inside the single
columns.bin (store.py) and reload via load_index."""
from __future__ import annotations

from typing import Any, Dict

from pinot_tpu.indexes.bloom import BloomFilter
from pinot_tpu.indexes.inverted import CompressedInvertedIndex, InvertedIndex, RangeEncodedIndex
from pinot_tpu.indexes.jsonidx import JsonIndex
from pinot_tpu.indexes.startree import StarTreeIndex
from pinot_tpu.indexes.text import TextIndex
from pinot_tpu.indexes.vector import VectorIndex

_REGISTRY = {
    InvertedIndex.KIND: InvertedIndex,
    CompressedInvertedIndex.KIND: CompressedInvertedIndex,
    RangeEncodedIndex.KIND: RangeEncodedIndex,
    BloomFilter.KIND: BloomFilter,
    StarTreeIndex.KIND: StarTreeIndex,
    JsonIndex.KIND: JsonIndex,
    TextIndex.KIND: TextIndex,
    VectorIndex.KIND: VectorIndex,
}


def register_index(kind: str, cls) -> None:
    _REGISTRY[kind] = cls


def load_index(kind: str, meta: Dict[str, Any], regions, prefix: str):
    # an index's meta may name a more specific implementation than its slot
    # (e.g. "cinverted" stored under the "inverted" slot)
    cls = _REGISTRY.get(meta.get("kind", kind)) or _REGISTRY.get(kind)
    if cls is None:
        raise ValueError(f"unknown index kind {kind!r} (have {list(_REGISTRY)})")
    return cls.from_regions(meta, regions, prefix)
