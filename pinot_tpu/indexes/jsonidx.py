"""JSON index: flattened path/value posting tables over the dictionary.

Reference parity: Pinot's JSON index (pinot-segment-local/.../index/json/ —
flattened path=value posting lists consumed by JsonMatchFilterOperator,
pinot-core/.../operator/filter/JsonMatchFilterOperator.java) and the
JSON_MATCH predicate grammar (key = value, nested paths, array [*] access,
AND/OR/NOT, IS [NOT] NULL).

Re-design: JSON columns are dictionary-encoded strings, so flattening runs
per DICTIONARY VALUE (cardinality work, not row work) into per-code path
maps; JSON_MATCH evaluates host-side over those maps into a bool CODE table,
and the device work is the same table[codes] lookup as any dictionary
predicate.  Arrays flatten under the path with "[*]"; Pinot's flattened-doc
semantics (one match within a single array element) collapse to ANY-element
semantics, documented delta."""
from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


def flatten_json(doc: Any, prefix: str = "$") -> Dict[str, List[Any]]:
    """One JSON document -> {path: [scalar values]} (arrays under [*])."""
    out: Dict[str, List[Any]] = {}

    def walk(node: Any, path: str) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{path}.{k}")
        elif isinstance(node, list):
            for v in node:
                walk(v, f"{path}[*]")
        else:
            out.setdefault(path, []).append(node)

    walk(doc, prefix)
    return out


class JsonIndex:
    KIND = "json"

    def __init__(self, flattened: List[Dict[str, List[Any]]]):
        # flattened[code] = {path: [values]} for dictionary entry `code`
        self.flattened = flattened

    @staticmethod
    def build(dict_values: np.ndarray) -> "JsonIndex":
        flat: List[Dict[str, List[Any]]] = []
        for v in dict_values:
            try:
                flat.append(flatten_json(json.loads(v)))
            except (json.JSONDecodeError, TypeError):
                flat.append({})
        return JsonIndex(flat)

    # -- JSON_MATCH evaluation -> bool table over codes -------------------
    def match(self, condition: str) -> np.ndarray:
        pred = _JsonMatchParser(condition).parse()
        return np.array([pred(f) for f in self.flattened], dtype=bool)

    # -- persistence ------------------------------------------------------
    def to_regions(self, prefix: str):
        payload = json.dumps(self.flattened).encode("utf-8")
        return [(f"{prefix}.paths", np.frombuffer(payload, dtype=np.uint8))]

    def meta(self) -> Dict[str, Any]:
        return {"kind": self.KIND}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "JsonIndex":
        payload = bytes(np.asarray(regions[f"{prefix}.paths"]))
        return JsonIndex(json.loads(payload.decode("utf-8")))


def _normalize_path(p: str) -> str:
    p = p.strip()
    if not p.startswith("$"):
        p = "$." + p
    # numeric array access "[0]" matches our "[*]" flattening (documented:
    # positional access degrades to ANY-element)
    return re.sub(r"\[\d+\]", "[*]", p)


class _JsonMatchParser:
    """Tiny recursive-descent parser for the JSON_MATCH condition grammar:
    '"$.a.b" = ''x''' | path != v | path > v | path IS [NOT] NULL |
    cond AND cond | cond OR cond | NOT cond | (cond)."""

    _TOKEN = re.compile(
        r"""\s*(?:
            (?P<lpar>\()|(?P<rpar>\))|
            (?P<op><=|>=|!=|<>|=|<|>)|
            (?P<kw>(?i:AND|OR|NOT|IS|NULL|IN))\b|
            (?P<str>'(?:[^']|'')*')|
            (?P<dstr>"(?:[^"]|"")*")|
            (?P<num>-?\d+(?:\.\d+)?)|
            (?P<word>[\w$.\[\]*]+)
        )""",
        re.VERBOSE,
    )

    def __init__(self, s: str):
        self.tokens: List[Tuple[str, str]] = []
        pos = 0
        while pos < len(s):
            m = self._TOKEN.match(s, pos)
            if not m:
                if s[pos:].strip() == "":
                    break
                raise ValueError(f"JSON_MATCH: cannot tokenize {s[pos:]!r}")
            pos = m.end()
            for k, v in m.groupdict().items():
                if v is not None:
                    self.tokens.append((k, v))
                    break
        self.i = 0

    def _peek(self) -> Optional[Tuple[str, str]]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def _next(self) -> Tuple[str, str]:
        t = self._peek()
        if t is None:
            raise ValueError("JSON_MATCH: unexpected end of condition")
        self.i += 1
        return t

    def _accept_kw(self, kw: str) -> bool:
        t = self._peek()
        if t and t[0] == "kw" and t[1].upper() == kw:
            self.i += 1
            return True
        return False

    def parse(self):
        node = self._or()
        if self._peek() is not None:
            raise ValueError(f"JSON_MATCH: trailing tokens {self.tokens[self.i:]}")
        return node

    def _or(self):
        left = self._and()
        while self._accept_kw("OR"):
            right = self._and()
            left = (lambda a, b: (lambda f: a(f) or b(f)))(left, right)
        return left

    def _and(self):
        left = self._unary()
        while self._accept_kw("AND"):
            right = self._unary()
            left = (lambda a, b: (lambda f: a(f) and b(f)))(left, right)
        return left

    def _unary(self):
        if self._accept_kw("NOT"):
            inner = self._unary()
            return lambda f: not inner(f)
        t = self._peek()
        if t and t[0] == "lpar":
            self.i += 1
            inner = self._or()
            k, _ = self._next()
            if k != "rpar":
                raise ValueError("JSON_MATCH: expected ')'")
            return inner
        return self._comparison()

    def _comparison(self):
        k, v = self._next()
        if k == "str":
            path = v[1:-1].replace("''", "'")
        elif k == "dstr":
            path = v[1:-1].replace('""', '"')
        elif k == "word":
            path = v
        else:
            raise ValueError(f"JSON_MATCH: expected a path, got {v!r}")
        path = _normalize_path(path)
        if self._accept_kw("IS"):
            neg = self._accept_kw("NOT")
            if not self._accept_kw("NULL"):
                raise ValueError("JSON_MATCH: expected NULL after IS [NOT]")
            if neg:
                return lambda f: path in f  # IS NOT NULL = path exists
            return lambda f: path not in f
        k2, op = self._next()
        if k2 != "op":
            raise ValueError(f"JSON_MATCH: expected an operator after {path!r}, got {op!r}")
        vk, vv = self._next()
        if vk == "str":
            val: Any = vv[1:-1].replace("''", "'")
        elif vk == "num":
            val = float(vv) if "." in vv else int(vv)
        elif vk == "word":
            val = {"true": True, "false": False}.get(vv.lower(), vv)
        else:
            raise ValueError(f"JSON_MATCH: bad literal {vv!r}")

        def cmp(f: Dict[str, List[Any]]) -> bool:
            vals = f.get(path)
            if vals is None:
                return False
            for x in vals:
                try:
                    if op == "=" and _eq(x, val):
                        return True
                    if op in ("!=", "<>") and not _eq(x, val):
                        return True
                    if op == "<" and x < val:
                        return True
                    if op == "<=" and x <= val:
                        return True
                    if op == ">" and x > val:
                        return True
                    if op == ">=" and x >= val:
                        return True
                except TypeError:
                    continue
            return False

        return cmp


def _eq(a: Any, b: Any) -> bool:
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b or a == b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    return a == b
