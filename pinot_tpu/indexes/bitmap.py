"""Dense bitmask utilities — the TPU replacement for RoaringBitmap.

Reference parity: RoaringBitmap underpins Pinot's inverted/range/json/null
indexes and filter algebra (SURVEY.md 2.4).  On TPU, compressed sparse bitmaps
are hostile to vector units; dense uint32 word tensors are native: AND/OR/NOT
are elementwise ops, cardinality is a popcount-reduce, and doc masks unpack
with shifts.  Layout: bit j of word w == doc (w*32 + j), LSB-first.
"""
from __future__ import annotations

import numpy as np

WORD_BITS = 32


def num_words(num_docs: int) -> int:
    return (num_docs + WORD_BITS - 1) // WORD_BITS


def pack_mask(mask: np.ndarray) -> np.ndarray:
    """bool[n] -> uint32[ceil(n/32)] (host side, build time)."""
    n = len(mask)
    bits = np.packbits(np.asarray(mask, dtype=bool), bitorder="little")
    pad = (-len(bits)) % 4
    if pad:
        bits = np.concatenate([bits, np.zeros(pad, dtype=np.uint8)])
    return bits.view(np.uint32).copy()


def unpack_mask(words: np.ndarray, n: int) -> np.ndarray:
    """uint32[w] -> bool[n] (host side)."""
    return np.unpackbits(np.asarray(words, dtype=np.uint32).view(np.uint8), bitorder="little", count=n).astype(bool)


def unpack_mask_device(words, n: int):
    """uint32[w] -> bool[n] on device: shift-and-mask, static shapes."""
    import jax.numpy as jnp

    w = words.shape[0]
    bits = (words[:, None] >> jnp.arange(WORD_BITS, dtype=words.dtype)[None, :]) & 1
    return bits.reshape(w * WORD_BITS)[:n].astype(bool)


def popcount_device(words):
    """Total set bits of a uint32 word tensor (device)."""
    import jax.lax as lax
    import jax.numpy as jnp

    return jnp.sum(lax.population_count(words).astype(jnp.int32))
