"""Star-tree index: pre-aggregated prefix-level tensors.

Reference parity: Pinot's StarTreeV2 — a materialized tree over a dimension
split order where star (*) nodes pre-aggregate over the remaining dimensions,
letting group-by queries answer from aggregated records instead of scanning
raw rows (pinot-segment-spi/.../spi/index/startree/StarTreeV2.java, builder
pinot-segment-local/.../startree/v2/builder/OffHeapSingleTreeBuilder.java,
runtime pinot-core/.../core/startree/operator/StarTreeFilterOperator.java:90,
traversal :218, StarTreeAggregationExecutor/StarTreeGroupByExecutor).

TPU re-design — the tree becomes a LADDER OF COLLAPSED TABLES. A pointer
tree with star-node traversal is a branchy, dynamic-shape structure XLA cannot
compile; but its *content* is equivalent to: for every prefix of the split
order, the table of distinct prefix combos with metrics pre-aggregated over
all other columns.  So we materialize exactly that — for each prefix length
k, a small columnar table ("level") of the distinct (d1..dk) combos with
pre-aggregated partial FIELDS (count/sum/sumsq/min/max per metric).  A query
whose filter+group-by columns all fall in the first k dims answers from
level k: same filter compiler, same group-key packing, same partial-field
contracts as the raw-scan path — just over collapsed rows.  Star-node
traversal becomes *level selection*, a host-side O(1) decision.

Level dimension columns share the PARENT segment's dictionaries (codes are
parent codes), so star results and raw-scan results from other segments merge
in the same key space at reduce time.

Pinot's functionColumnPairs config maps 1:1; maxLeafRecords is accepted but
moot here (every "leaf" is one aggregated row); instead `min_collapse`
skips building when the finest level barely collapses the data.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from pinot_tpu.query.functions import get_agg_function
from pinot_tpu.segment.stats import ColumnStats

# field kinds stored per metric column (count is global: "*:count")
_ADDITIVE = ("sum", "sumsq")
_MINMAX = ("min", "max")


def scatter_combine(kind: str, inverse: np.ndarray, vals: np.ndarray, n_groups: int) -> np.ndarray:
    """One (count|sum|sumsq|min|max) scatter-aggregate into n_groups slots —
    the single combine rule shared by the finest-level build, the coarser-level
    rollup, and the star-served group-by path.  Additive integer kinds
    accumulate exactly in int64; float kinds use bincount; min/max use ufunc
    scatter.  `vals` is taken as-is (callers square before passing sumsq of
    raw rows; partials re-combine without squaring)."""
    vals = np.asarray(vals)
    if kind in ("count", "sum", "sumsq"):
        if np.issubdtype(vals.dtype, np.integer) and kind != "sumsq":
            acc = np.zeros(n_groups, dtype=np.int64)
            np.add.at(acc, inverse, vals.astype(np.int64, copy=False))
            return acc
        return np.bincount(inverse, weights=vals.astype(np.float64, copy=False), minlength=n_groups)
    if kind == "min":
        acc = np.full(n_groups, np.inf)
        np.minimum.at(acc, inverse, vals.astype(np.float64, copy=False))
        return acc
    if kind == "max":
        acc = np.full(n_groups, -np.inf)
        np.maximum.at(acc, inverse, vals.astype(np.float64, copy=False))
        return acc
    raise ValueError(f"unknown star-tree field kind {kind!r}")


def _parse_pairs(pairs: List[Any]) -> List[Tuple[str, str]]:
    """functionColumnPairs: "SUM__lo_revenue" strings or [func, col] lists."""
    out = []
    for p in pairs:
        if isinstance(p, str):
            func, _, col = p.partition("__")
        else:
            func, col = p
        out.append((func.lower(), col))
    return out


class StarTreeIndex:
    KIND = "startree"

    def __init__(
        self,
        split_order: List[str],
        pairs: List[Tuple[str, str]],
        levels: Dict[int, "StarLevel"],
        total_docs: int,
    ):
        self.split_order = list(split_order)
        self.pairs = [(f.lower(), c) for f, c in pairs]
        self.levels = levels
        self.total_docs = total_docs
        # (col, kind) set actually stored (derived from level 0's fields)
        any_level = next(iter(levels.values()))
        self.stored: frozenset = frozenset(any_level.fields)

    # ------------------------------------------------------------------
    @staticmethod
    def build(
        columns: Dict[str, Any],
        num_docs: int,
        split_order: List[str],
        function_column_pairs: List[Any],
        min_collapse: float = 1.1,
    ) -> Optional["StarTreeIndex"]:
        """Build the level ladder from a segment's columns.

        Returns None (tree not worth it / not buildable) when: a dim or
        metric column has nulls, a metric is non-numeric, or the finest
        level collapses rows by less than `min_collapse`x."""
        pairs = _parse_pairs(function_column_pairs)

        # dim code matrix [n, k]: parent dict codes, or raw ints as-is
        dim_mat = []
        for d in split_order:
            c = columns.get(d)
            if c is None or c.nulls is not None:
                return None
            if c.codes is not None:
                dim_mat.append(np.asarray(c.codes, dtype=np.int64))
            elif c.values is not None and np.issubdtype(np.asarray(c.values).dtype, np.integer):
                dim_mat.append(np.asarray(c.values, dtype=np.int64))
            else:
                return None

        # metric field columns to aggregate: (col, kind) -> source values
        need: Dict[Tuple[str, str], np.ndarray] = {}
        for func, col in pairs:
            if col == "*":
                continue
            c = columns.get(col)
            if c is None or c.nulls is not None:
                return None
            vals = np.asarray(c.decoded())
            if not np.issubdtype(vals.dtype, np.number):
                return None
            fn = get_agg_function(func)
            if fn.field_kinds is None:
                return None  # sketch family: not pre-aggregable as scalars
            for kind in fn.field_kinds.values():
                if kind == "count":
                    continue
                need[(col, kind)] = vals

        mat = np.stack(dim_mat, axis=1) if dim_mat else np.zeros((num_docs, 0), np.int64)
        finest, inverse = np.unique(mat, axis=0, return_inverse=True)
        if len(finest) * min_collapse > num_docs:
            return None  # barely collapses: scanning raw rows is as cheap

    # finest level: aggregate raw rows into the distinct-combo table
        n_g = len(finest)
        fields: Dict[Tuple[str, str], np.ndarray] = {}
        fields[("*", "count")] = np.bincount(inverse, minlength=n_g).astype(np.int64)
        for (col, kind), vals in need.items():
            src = vals.astype(np.float64) ** 2 if kind == "sumsq" else vals
            fields[(col, kind)] = scatter_combine(kind, inverse, src, n_g)

        K = len(split_order)
        levels: Dict[int, StarLevel] = {
            K: StarLevel(
                num_rows=n_g,
                dims={d: finest[:, i].copy() for i, d in enumerate(split_order)},
                fields=fields,
            )
        }
        # coarser levels: aggregate the next-finer level (adds add, mins min)
        cur = finest  # combo matrix aligned with levels[k + 1]'s rows
        for k in range(K - 1, -1, -1):
            finer = levels[k + 1]
            sub = cur[:, :k] if k else np.zeros((len(cur), 0), np.int64)
            combos, inv2 = np.unique(sub, axis=0, return_inverse=True)
            m = len(combos)
            f2: Dict[Tuple[str, str], np.ndarray] = {}
            for (col, kind), arr in finer.fields.items():
                f2[(col, kind)] = scatter_combine(kind, inv2, arr, m)
            levels[k] = StarLevel(
                num_rows=m,
                dims={d: combos[:, i].copy() for i, d in enumerate(split_order[:k])},
                fields=f2,
            )
            cur = combos
        return StarTreeIndex(split_order, pairs, levels, num_docs)

    # -- persistence (store.py region protocol) -------------------------
    def to_regions(self, prefix: str) -> List[Tuple[str, np.ndarray]]:
        regions = []
        for k, lvl in self.levels.items():
            for d, arr in lvl.dims.items():
                regions.append((f"{prefix}.L{k}.d.{d}", arr))
            for (col, kind), arr in lvl.fields.items():
                regions.append((f"{prefix}.L{k}.f.{col}:{kind}", arr))
        return regions

    def meta(self) -> Dict[str, Any]:
        return {
            "splitOrder": self.split_order,
            "pairs": [[f, c] for f, c in self.pairs],
            "levels": {str(k): lvl.num_rows for k, lvl in self.levels.items()},
            "fields": [[c, k] for c, k in sorted(self.stored)],
            "totalDocs": self.total_docs,
        }

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "StarTreeIndex":
        split_order = meta["splitOrder"]
        levels: Dict[int, StarLevel] = {}
        for ks, nrows in meta["levels"].items():
            k = int(ks)
            dims = {
                d: np.asarray(regions[f"{prefix}.L{k}.d.{d}"]) for d in split_order[:k]
            }
            fields = {
                (c, kd): np.asarray(regions[f"{prefix}.L{k}.f.{c}:{kd}"])
                for c, kd in meta["fields"]
            }
            levels[k] = StarLevel(num_rows=nrows, dims=dims, fields=fields)
        return StarTreeIndex(
            split_order, [tuple(p) for p in meta["pairs"]], levels, meta["totalDocs"]
        )

    # -- query-time API --------------------------------------------------
    def level_for(self, dims_used: set) -> Optional[int]:
        """Smallest prefix length covering dims_used, or None."""
        if not dims_used <= set(self.split_order):
            return None
        k = 0
        for i, d in enumerate(self.split_order):
            if d in dims_used:
                k = i + 1
        return k

    def has_fields(self, func: str, col: str) -> bool:
        fn = get_agg_function(func)
        if fn.field_kinds is None or fn.needs_binding:
            return False
        for kind in fn.field_kinds.values():
            key = ("*", "count") if kind == "count" else (col, kind)
            if key not in self.stored:
                return False
        return True


class StarLevel:
    """One collapsed table: distinct prefix combos + aggregated fields."""

    def __init__(
        self,
        num_rows: int,
        dims: Dict[str, np.ndarray],
        fields: Dict[Tuple[str, str], np.ndarray],
    ):
        self.num_rows = num_rows
        self.dims = dims
        self.fields = fields

    def facade(self, parent) -> "_StarSegmentView":
        """Segment-shaped view over this level for FilterCompiler/_group_dim:
        dim columns carry the PARENT's dictionaries over the level's codes."""
        return _StarSegmentView(self, parent)


class _StarSegmentView:
    """Duck-typed ImmutableSegment over one star level (dims only)."""

    def __init__(self, level: StarLevel, parent):
        from pinot_tpu.segment.segment import ColumnData

        self.num_docs = level.num_rows
        self.schema = parent.schema
        self.indexes: Dict[str, Dict[str, Any]] = {}
        self.columns: Dict[str, ColumnData] = {}
        for name, arr in level.dims.items():
            pc = parent.column(name)
            if pc.has_dictionary:
                codes = arr.astype(np.min_scalar_type(max(1, pc.dictionary.cardinality - 1)))
                mn = pc.dictionary.get_values(np.array([arr.min()]))[0] if len(arr) else None
                mx = pc.dictionary.get_values(np.array([arr.max()]))[0] if len(arr) else None
                stats = ColumnStats(
                    name=name, data_type=pc.data_type, num_docs=level.num_rows,
                    cardinality=pc.dictionary.cardinality, min_value=mn, max_value=mx,
                    is_sorted=bool(len(arr) < 2 or np.all(np.diff(arr) >= 0)),
                    has_nulls=False, has_dictionary=True,
                )
                self.columns[name] = ColumnData(
                    name, pc.data_type, pc.dictionary, codes, None, None, stats
                )
            else:
                vals = arr.astype(pc.values.dtype)
                stats = ColumnStats(
                    name=name, data_type=pc.data_type, num_docs=level.num_rows,
                    cardinality=len(np.unique(arr)),
                    min_value=arr.min() if len(arr) else None,
                    max_value=arr.max() if len(arr) else None,
                    is_sorted=bool(len(arr) < 2 or np.all(np.diff(arr) >= 0)),
                    has_nulls=False, has_dictionary=False,
                )
                self.columns[name] = ColumnData(
                    name, pc.data_type, None, None, vals, None, stats
                )

    def column(self, name: str):
        try:
            return self.columns[name]
        except KeyError:
            raise KeyError(f"star level has no dimension column {name!r}") from None
