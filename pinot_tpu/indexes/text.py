"""Text index: tokenized posting tables over the dictionary.

Reference parity: Pinot's Lucene-backed text index
(pinot-segment-local/.../index/text/, consumed by TEXT_MATCH through
TextMatchFilterOperator) plus the native-FST regex dictionaries
(pinot-segment-local/.../segment/local/utils/nativefst/).  Re-design:
strings are dictionary-encoded, so tokenization runs per DICTIONARY VALUE
into token -> code-bitmap tables; TEXT_MATCH queries evaluate host-side
into one bool code table and the device does the usual table[codes]
lookup.  Query grammar: terms (implicit AND), OR, NOT, "quoted phrase"
(substring), trailing-* prefixes, /regex/ terms (RE over the token
dictionary — the FST-regex analog, O(tokens) not O(rows)), mid-token
wildcards (te*m, t?m), and term~N fuzzy matching (banded Levenshtein over
the token dictionary; ~ defaults to distance 2 like Lucene).  Documented
delta: no boosts / fields."""
from __future__ import annotations

import re
from typing import Any, Dict, List

import numpy as np

_TOKEN_RX = re.compile(r"[A-Za-z0-9_]+")


def tokenize(text: str) -> List[str]:
    return [t.lower() for t in _TOKEN_RX.findall(text)]


class TextIndex:
    KIND = "text"

    def __init__(self, tokens: Dict[str, np.ndarray], values: np.ndarray):
        self.tokens = tokens  # token -> bool[cardinality]
        self.values = values  # original dictionary values (phrase queries)

    @staticmethod
    def build(dict_values: np.ndarray) -> "TextIndex":
        card = len(dict_values)
        tokens: Dict[str, np.ndarray] = {}
        for code, v in enumerate(dict_values):
            for t in set(tokenize(str(v))):
                tbl = tokens.get(t)
                if tbl is None:
                    tbl = tokens[t] = np.zeros(card, dtype=bool)
                tbl[code] = True
        return TextIndex(tokens, np.asarray(dict_values, dtype=object))

    # -- TEXT_MATCH evaluation -> bool table over codes --------------------
    def match(self, query: str) -> np.ndarray:
        card = len(self.values)
        terms = self._parse(query)
        if not terms:
            return np.zeros(card, dtype=bool)
        # OR groups of AND terms
        result = np.zeros(card, dtype=bool)
        for group in terms:
            g = np.ones(card, dtype=bool)
            for negate, kind, term in group:
                t = self._eval_term(kind, term, card)
                g &= ~t if negate else t
            result |= g
        return result

    def _eval_term(self, kind: str, term, card: int) -> np.ndarray:
        if kind == "phrase":
            needle = term.lower()
            return np.array([needle in str(v).lower() for v in self.values], dtype=bool)
        if kind == "prefix":
            out = np.zeros(card, dtype=bool)
            for tok, tbl in self.tokens.items():
                if tok.startswith(term):
                    out |= tbl
            return out
        if kind == "regex":
            # regex over the TOKEN DICTIONARY, never the rows — the same
            # O(distinct tokens) trade as the reference's FST regex
            rx = re.compile(term)
            out = np.zeros(card, dtype=bool)
            for tok, tbl in self.tokens.items():
                if rx.fullmatch(tok):
                    out |= tbl
            return out
        if kind == "fuzzy":
            base, dist = term
            out = np.zeros(card, dtype=bool)
            for tok, tbl in self.tokens.items():
                if abs(len(tok) - len(base)) <= dist and _edit_within(base, tok, dist):
                    out |= tbl
            return out
        tbl = self.tokens.get(term)
        return tbl.copy() if tbl is not None else np.zeros(card, dtype=bool)

    @staticmethod
    def _parse(query: str):
        """-> list of OR-groups, each a list of (negate, kind, term)."""
        groups: List[List] = [[]]
        pos = 0
        rx = re.compile(r'\s*(?:(?P<or>(?i:OR))\b|(?P<not>(?i:NOT))\b|(?P<phrase>"[^"]*")|(?P<term>\S+))')
        pending_not = False
        while pos < len(query):
            m = rx.match(query, pos)
            if not m:
                break
            pos = m.end()
            if m.group("or"):
                groups.append([])
                pending_not = False
            elif m.group("not"):
                pending_not = True
            elif m.group("phrase"):
                groups[-1].append((pending_not, "phrase", m.group("phrase")[1:-1]))
                pending_not = False
            else:
                raw = m.group("term")
                if len(raw) >= 2 and raw.startswith("/") and raw.endswith("/"):
                    # /regex/ term (Lucene RegexpQuery syntax); tokens are
                    # lowercase, so the pattern compiles case-insensitively
                    groups[-1].append((pending_not, "regex", f"(?i:{raw[1:-1]})"))
                    pending_not = False
                    continue
                term = raw.lower()
                fz = re.fullmatch(r"(.+?)~(\d*)", term)
                if fz:
                    dist = int(fz.group(2)) if fz.group(2) else 2
                    groups[-1].append((pending_not, "fuzzy", (fz.group(1), dist)))
                elif term.endswith("*") and "*" not in term[:-1] and "?" not in term:
                    groups[-1].append((pending_not, "prefix", term.rstrip("*")))
                elif "*" in term or "?" in term:
                    # mid-token wildcards -> anchored regex over tokens
                    pat = "".join(
                        ".*" if ch == "*" else "." if ch == "?" else re.escape(ch)
                        for ch in term
                    )
                    groups[-1].append((pending_not, "regex", pat))
                else:
                    groups[-1].append((pending_not, "term", term))
                pending_not = False
        return [g for g in groups if g]

    # -- persistence -------------------------------------------------------
    def to_regions(self, prefix: str):
        import json

        payload = json.dumps({t: np.nonzero(tbl)[0].tolist() for t, tbl in self.tokens.items()}).encode()
        return [(f"{prefix}.tokens", np.frombuffer(payload, dtype=np.uint8))]

    def meta(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "cardinality": len(self.values)}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str, dict_values=None) -> "TextIndex":
        import json

        card = meta["cardinality"]
        raw = json.loads(bytes(np.asarray(regions[f"{prefix}.tokens"])).decode())
        tokens = {}
        for t, codes in raw.items():
            tbl = np.zeros(card, dtype=bool)
            tbl[np.asarray(codes, dtype=np.int64)] = True
            tokens[t] = tbl
        vals = dict_values if dict_values is not None else np.array([""] * card, dtype=object)
        return TextIndex(tokens, vals)


def _edit_within(a: str, b: str, k: int) -> bool:
    """Banded Levenshtein: True iff edit distance(a, b) <= k (the fuzzy-term
    predicate; band width 2k+1 keeps it O(len * k))."""
    la, lb = len(a), len(b)
    if abs(la - lb) > k:
        return False
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        lo = max(1, i - k)
        hi = min(lb, i + k)
        if lo > 1:
            cur[lo - 1] = k + 1
        for j in range(lo, hi + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        if hi < lb:
            cur[hi + 1 :] = [k + 1] * (lb - hi)
        prev = cur
        if min(prev[lo - 1 : hi + 1]) > k:
            return False
    return prev[lb] <= k
