"""Text index: tokenized posting tables over the dictionary.

Reference parity: Pinot's Lucene-backed text index
(pinot-segment-local/.../index/text/, consumed by TEXT_MATCH through
TextMatchFilterOperator).  Re-design: strings are dictionary-encoded, so
tokenization runs per DICTIONARY VALUE into token -> code-bitmap tables;
TEXT_MATCH queries evaluate host-side into one bool code table and the
device does the usual table[codes] lookup.  Query grammar: terms (implicit
AND), OR, NOT, "quoted phrase" (substring), trailing-* prefix wildcards —
the commonly-used subset of Lucene query syntax (documented delta: no fuzzy
/ boosts / fields)."""
from __future__ import annotations

import re
from typing import Any, Dict, List

import numpy as np

_TOKEN_RX = re.compile(r"[A-Za-z0-9_]+")


def tokenize(text: str) -> List[str]:
    return [t.lower() for t in _TOKEN_RX.findall(text)]


class TextIndex:
    KIND = "text"

    def __init__(self, tokens: Dict[str, np.ndarray], values: np.ndarray):
        self.tokens = tokens  # token -> bool[cardinality]
        self.values = values  # original dictionary values (phrase queries)

    @staticmethod
    def build(dict_values: np.ndarray) -> "TextIndex":
        card = len(dict_values)
        tokens: Dict[str, np.ndarray] = {}
        for code, v in enumerate(dict_values):
            for t in set(tokenize(str(v))):
                tbl = tokens.get(t)
                if tbl is None:
                    tbl = tokens[t] = np.zeros(card, dtype=bool)
                tbl[code] = True
        return TextIndex(tokens, np.asarray(dict_values, dtype=object))

    # -- TEXT_MATCH evaluation -> bool table over codes --------------------
    def match(self, query: str) -> np.ndarray:
        card = len(self.values)
        terms = self._parse(query)
        if not terms:
            return np.zeros(card, dtype=bool)
        # OR groups of AND terms
        result = np.zeros(card, dtype=bool)
        for group in terms:
            g = np.ones(card, dtype=bool)
            for negate, kind, term in group:
                t = self._eval_term(kind, term, card)
                g &= ~t if negate else t
            result |= g
        return result

    def _eval_term(self, kind: str, term: str, card: int) -> np.ndarray:
        if kind == "phrase":
            needle = term.lower()
            return np.array([needle in str(v).lower() for v in self.values], dtype=bool)
        if kind == "prefix":
            out = np.zeros(card, dtype=bool)
            for tok, tbl in self.tokens.items():
                if tok.startswith(term):
                    out |= tbl
            return out
        tbl = self.tokens.get(term)
        return tbl.copy() if tbl is not None else np.zeros(card, dtype=bool)

    @staticmethod
    def _parse(query: str):
        """-> list of OR-groups, each a list of (negate, kind, term)."""
        groups: List[List] = [[]]
        pos = 0
        rx = re.compile(r'\s*(?:(?P<or>(?i:OR))\b|(?P<not>(?i:NOT))\b|(?P<phrase>"[^"]*")|(?P<term>\S+))')
        pending_not = False
        while pos < len(query):
            m = rx.match(query, pos)
            if not m:
                break
            pos = m.end()
            if m.group("or"):
                groups.append([])
                pending_not = False
            elif m.group("not"):
                pending_not = True
            elif m.group("phrase"):
                groups[-1].append((pending_not, "phrase", m.group("phrase")[1:-1]))
                pending_not = False
            else:
                term = m.group("term").lower()
                kind = "prefix" if term.endswith("*") else "term"
                groups[-1].append((pending_not, kind, term.rstrip("*")))
                pending_not = False
        return [g for g in groups if g]

    # -- persistence -------------------------------------------------------
    def to_regions(self, prefix: str):
        import json

        payload = json.dumps({t: np.nonzero(tbl)[0].tolist() for t, tbl in self.tokens.items()}).encode()
        return [(f"{prefix}.tokens", np.frombuffer(payload, dtype=np.uint8))]

    def meta(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "cardinality": len(self.values)}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str, dict_values=None) -> "TextIndex":
        import json

        card = meta["cardinality"]
        raw = json.loads(bytes(np.asarray(regions[f"{prefix}.tokens"])).decode())
        tokens = {}
        for t, codes in raw.items():
            tbl = np.zeros(card, dtype=bool)
            tbl[np.asarray(codes, dtype=np.int64)] = True
            tokens[t] = tbl
        vals = dict_values if dict_values is not None else np.array([""] * card, dtype=object)
        return TextIndex(tokens, vals)
