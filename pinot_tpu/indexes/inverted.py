"""Inverted + range-encoded bitmap indexes as dense HBM tensors.

Reference parity:
  * Inverted: dictId -> bitmap of docIds (pinot-segment-local
    BitmapInvertedIndexReader; creator in .../segment/creator/impl/inv/).
  * Range: bucketed ranges -> bitmaps answering >, <, BETWEEN
    (RangeIndexReader + RangeIndexBasedFilterOperator).

TPU re-design: both become one 2-D uint32 bitmask tensor.
  * InvertedIndex: rows = per-dictId doc bitmaps, shape (card, words).
    EQ(v) = one row load (n/8 bytes instead of n..4n for a code scan);
    IN(set) = OR of k rows.
  * RangeEncodedIndex: rows = PREFIX bitmaps, prefix[i] = docs with code < i,
    shape (card+1, words).  range[lo,hi) = prefix[hi] AND NOT prefix[lo] —
    two row loads for ANY range width (better than Pinot's bucket scheme,
    which still scans bucket interiors).  EQ also derivable, so a column with
    a range index doesn't need a separate inverted index.

Only built for cardinality <= threshold (builder default 64k rows of words):
for high-cardinality columns a vectorized code scan is already HBM-optimal on
TPU, matching Pinot's own guidance that inverted indexes pay off on
low-cardinality filter columns.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from pinot_tpu.indexes.bitmap import num_words, WORD_BITS


def _bitmaps_from_codes(codes: np.ndarray, cardinality: int, num_docs: int) -> np.ndarray:
    """Build (cardinality, words) doc bitmaps from the code array in one
    vectorized pass (the off-heap creator analog)."""
    words = num_words(num_docs)
    out = np.zeros((cardinality, words), dtype=np.uint32)
    docs = np.arange(num_docs, dtype=np.int64)
    w = docs >> 5
    bit = np.uint32(1) << (docs & 31).astype(np.uint32)
    # scatter-OR per (code, word); np.bitwise_or.at handles duplicates.
    np.bitwise_or.at(out, (codes.astype(np.int64), w), bit)
    return out


class InvertedIndex:
    """Per-dictId doc bitmaps: shape (cardinality, words)."""

    KIND = "inverted"

    def __init__(self, bitmaps: np.ndarray, num_docs: int):
        self.bitmaps = bitmaps
        self.num_docs = num_docs
        self._device = None

    @staticmethod
    def build(codes: np.ndarray, cardinality: int, num_docs: int) -> "InvertedIndex":
        return InvertedIndex(_bitmaps_from_codes(codes, cardinality, num_docs), num_docs)

    @property
    def cardinality(self) -> int:
        return self.bitmaps.shape[0]

    @property
    def num_words(self) -> int:
        return self.bitmaps.shape[1]

    def device(self, device=None):
        if self._device is None:
            import jax

            self._device = jax.device_put(self.bitmaps, device)
        return self._device

    # host-side eval (tests / host executor)
    def doc_bitmap(self, dict_ids) -> np.ndarray:
        rows = self.bitmaps[np.asarray(dict_ids, dtype=np.int64)]
        return np.bitwise_or.reduce(rows, axis=0) if rows.ndim == 2 else rows

    # serde
    def to_regions(self, prefix: str):
        yield f"{prefix}.bitmaps", self.bitmaps

    def meta(self) -> Dict[str, Any]:
        return {"numDocs": self.num_docs, "cardinality": int(self.bitmaps.shape[0])}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "InvertedIndex":
        return InvertedIndex(np.asarray(regions[f"{prefix}.bitmaps"]), meta["numDocs"])


class CompressedInvertedIndex:
    """Sparse inverted index: per-dictId COMPRESSED posting bitmaps
    (utils/bitmaps.py roaring-style codec over native/bitmap.cc).

    Total storage is O(num_docs) — each doc appears in exactly one posting —
    vs the dense tensor's O(cardinality x num_docs/8), which at 100k codes
    over 1B rows would be terabytes (round-2 verdict weak #7).  Query-time
    EQ/IN decompresses only the requested rows into one dense word mask
    (the same param the dense index ships)."""

    KIND = "cinverted"

    def __init__(self, blobs: np.ndarray, offsets: np.ndarray, num_docs: int):
        self.blobs = blobs  # uint8 concatenated compressed rows
        self.offsets = offsets  # int64[card+1]
        self.num_docs = num_docs

    @staticmethod
    def build(codes: np.ndarray, cardinality: int, num_docs: int) -> "CompressedInvertedIndex":
        from pinot_tpu.utils import bitmaps

        order = np.argsort(codes, kind="stable")
        sorted_codes = codes[order]
        docs = order.astype(np.uint32)
        starts = np.searchsorted(sorted_codes, np.arange(cardinality + 1))
        parts = []
        offsets = np.zeros(cardinality + 1, dtype=np.int64)
        pos = 0
        for c in range(cardinality):
            row_docs = np.sort(docs[starts[c] : starts[c + 1]])
            blob = bitmaps.compress(row_docs)
            parts.append(np.frombuffer(blob, dtype=np.uint8))
            pos += len(blob)
            offsets[c + 1] = pos
        blobs = np.concatenate(parts) if parts else np.zeros(0, np.uint8)
        return CompressedInvertedIndex(blobs, offsets, num_docs)

    @property
    def cardinality(self) -> int:
        return len(self.offsets) - 1

    @property
    def num_words(self) -> int:
        return num_words(self.num_docs)

    def doc_bitmap(self, dict_ids) -> np.ndarray:
        """OR of the requested posting rows as dense u32 words."""
        from pinot_tpu.utils import bitmaps

        words = np.zeros(self.num_words, dtype=np.uint32)
        for c in np.atleast_1d(np.asarray(dict_ids, dtype=np.int64)):
            lo, hi = int(self.offsets[c]), int(self.offsets[c + 1])
            if hi > lo:
                bitmaps.decompress_into_words(self.blobs[lo:hi].tobytes(), words)
        return words

    def to_regions(self, prefix: str):
        yield f"{prefix}.blobs", self.blobs
        yield f"{prefix}.offsets", self.offsets

    def meta(self) -> Dict[str, Any]:
        return {"kind": self.KIND, "numDocs": self.num_docs}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "CompressedInvertedIndex":
        return CompressedInvertedIndex(
            np.asarray(regions[f"{prefix}.blobs"]),
            np.asarray(regions[f"{prefix}.offsets"]),
            meta["numDocs"],
        )


class RangeEncodedIndex:
    """Prefix bitmaps: prefix[i] = docs with code < i; shape (card+1, words).

    range [lo, hi) = prefix[hi] & ~prefix[lo] (prefix[lo] subset of
    prefix[hi]), i.e. two row loads per range predicate."""

    KIND = "range"

    def __init__(self, prefix: np.ndarray, num_docs: int):
        self.prefix = prefix
        self.num_docs = num_docs
        self._device = None

    @staticmethod
    def build(codes: np.ndarray, cardinality: int, num_docs: int) -> "RangeEncodedIndex":
        per_value = _bitmaps_from_codes(codes, cardinality, num_docs)
        prefix = np.zeros((cardinality + 1, per_value.shape[1]), dtype=np.uint32)
        np.bitwise_or.accumulate(per_value, axis=0, out=per_value)
        prefix[1:] = per_value
        return RangeEncodedIndex(prefix, num_docs)

    @property
    def cardinality(self) -> int:
        return self.prefix.shape[0] - 1

    def device(self, device=None):
        if self._device is None:
            import jax

            self._device = jax.device_put(self.prefix, device)
        return self._device

    def range_bitmap(self, lo: int, hi: int) -> np.ndarray:
        """Docs with lo <= code < hi (host side)."""
        lo = max(0, min(lo, self.cardinality))
        hi = max(lo, min(hi, self.cardinality))
        return self.prefix[hi] & ~self.prefix[lo]

    def to_regions(self, prefix: str):
        yield f"{prefix}.prefix", self.prefix

    def meta(self) -> Dict[str, Any]:
        return {"numDocs": self.num_docs, "cardinality": int(self.prefix.shape[0] - 1)}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "RangeEncodedIndex":
        return RangeEncodedIndex(np.asarray(regions[f"{prefix}.prefix"]), meta["numDocs"])
