"""Inverted + range-encoded bitmap indexes as dense HBM tensors.

Reference parity:
  * Inverted: dictId -> bitmap of docIds (pinot-segment-local
    BitmapInvertedIndexReader; creator in .../segment/creator/impl/inv/).
  * Range: bucketed ranges -> bitmaps answering >, <, BETWEEN
    (RangeIndexReader + RangeIndexBasedFilterOperator).

TPU re-design: both become one 2-D uint32 bitmask tensor.
  * InvertedIndex: rows = per-dictId doc bitmaps, shape (card, words).
    EQ(v) = one row load (n/8 bytes instead of n..4n for a code scan);
    IN(set) = OR of k rows.
  * RangeEncodedIndex: rows = PREFIX bitmaps, prefix[i] = docs with code < i,
    shape (card+1, words).  range[lo,hi) = prefix[hi] AND NOT prefix[lo] —
    two row loads for ANY range width (better than Pinot's bucket scheme,
    which still scans bucket interiors).  EQ also derivable, so a column with
    a range index doesn't need a separate inverted index.

Only built for cardinality <= threshold (builder default 64k rows of words):
for high-cardinality columns a vectorized code scan is already HBM-optimal on
TPU, matching Pinot's own guidance that inverted indexes pay off on
low-cardinality filter columns.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from pinot_tpu.indexes.bitmap import num_words, WORD_BITS


def _bitmaps_from_codes(codes: np.ndarray, cardinality: int, num_docs: int) -> np.ndarray:
    """Build (cardinality, words) doc bitmaps from the code array in one
    vectorized pass (the off-heap creator analog)."""
    words = num_words(num_docs)
    out = np.zeros((cardinality, words), dtype=np.uint32)
    docs = np.arange(num_docs, dtype=np.int64)
    w = docs >> 5
    bit = np.uint32(1) << (docs & 31).astype(np.uint32)
    # scatter-OR per (code, word); np.bitwise_or.at handles duplicates.
    np.bitwise_or.at(out, (codes.astype(np.int64), w), bit)
    return out


class InvertedIndex:
    """Per-dictId doc bitmaps: shape (cardinality, words)."""

    KIND = "inverted"

    def __init__(self, bitmaps: np.ndarray, num_docs: int):
        self.bitmaps = bitmaps
        self.num_docs = num_docs
        self._device = None

    @staticmethod
    def build(codes: np.ndarray, cardinality: int, num_docs: int) -> "InvertedIndex":
        return InvertedIndex(_bitmaps_from_codes(codes, cardinality, num_docs), num_docs)

    @property
    def cardinality(self) -> int:
        return self.bitmaps.shape[0]

    def device(self, device=None):
        if self._device is None:
            import jax

            self._device = jax.device_put(self.bitmaps, device)
        return self._device

    # host-side eval (tests / host executor)
    def doc_bitmap(self, dict_ids) -> np.ndarray:
        rows = self.bitmaps[np.asarray(dict_ids, dtype=np.int64)]
        return np.bitwise_or.reduce(rows, axis=0) if rows.ndim == 2 else rows

    # serde
    def to_regions(self, prefix: str):
        yield f"{prefix}.bitmaps", self.bitmaps

    def meta(self) -> Dict[str, Any]:
        return {"numDocs": self.num_docs, "cardinality": int(self.bitmaps.shape[0])}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "InvertedIndex":
        return InvertedIndex(np.asarray(regions[f"{prefix}.bitmaps"]), meta["numDocs"])


class RangeEncodedIndex:
    """Prefix bitmaps: prefix[i] = docs with code < i; shape (card+1, words).

    range [lo, hi) = prefix[hi] & ~prefix[lo] (prefix[lo] subset of
    prefix[hi]), i.e. two row loads per range predicate."""

    KIND = "range"

    def __init__(self, prefix: np.ndarray, num_docs: int):
        self.prefix = prefix
        self.num_docs = num_docs
        self._device = None

    @staticmethod
    def build(codes: np.ndarray, cardinality: int, num_docs: int) -> "RangeEncodedIndex":
        per_value = _bitmaps_from_codes(codes, cardinality, num_docs)
        prefix = np.zeros((cardinality + 1, per_value.shape[1]), dtype=np.uint32)
        np.bitwise_or.accumulate(per_value, axis=0, out=per_value)
        prefix[1:] = per_value
        return RangeEncodedIndex(prefix, num_docs)

    @property
    def cardinality(self) -> int:
        return self.prefix.shape[0] - 1

    def device(self, device=None):
        if self._device is None:
            import jax

            self._device = jax.device_put(self.prefix, device)
        return self._device

    def range_bitmap(self, lo: int, hi: int) -> np.ndarray:
        """Docs with lo <= code < hi (host side)."""
        lo = max(0, min(lo, self.cardinality))
        hi = max(lo, min(hi, self.cardinality))
        return self.prefix[hi] & ~self.prefix[lo]

    def to_regions(self, prefix: str):
        yield f"{prefix}.prefix", self.prefix

    def meta(self) -> Dict[str, Any]:
        return {"numDocs": self.num_docs, "cardinality": int(self.prefix.shape[0] - 1)}

    @staticmethod
    def from_regions(meta: Dict[str, Any], regions, prefix: str) -> "RangeEncodedIndex":
        return RangeEncodedIndex(np.asarray(regions[f"{prefix}.prefix"]), meta["numDocs"])
