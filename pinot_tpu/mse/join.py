"""Join kernel primitives: sorted build side + searchsorted probe.

Reference parity: HashJoinOperator's build/probe phases
(pinot-query-runtime/.../runtime/operator/HashJoinOperator.java — build a
key->rows hash table from the right input, probe with left rows).

Re-design: a TPU has no pointer-chasing hash table, but a sort plus binary
search IS a perfect hash for static shapes: sort the (filtered) build keys
once, then `searchsorted` every probe key in parallel — O(B log B + P log B)
of pure vector work that XLA maps onto the VPU.

Two variants: lookup_join for UNIQUE build keys (dimension primary keys,
one matched row per probe), and range_join for bounded many-to-many — the
planner computes the build side's MAX key multiplicity host-side (static)
and each probe returns up to max_dup matched rows as a [P, max_dup]
expansion.  The reference's hash join materializes variable-length match
lists; the static-shape analog pays max_dup slots for every probe row,
which is the TPU trade (dense over dynamic) and why the planner caps it.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

# sentinel: larger than any real key; invalid build rows sort to the end
KEY_SENTINEL = jnp.iinfo(jnp.int64).max


def lookup_join(
    build_keys: jnp.ndarray,  # int64 [B]
    build_valid: jnp.ndarray,  # bool [B]
    probe_keys: jnp.ndarray,  # int64 [P]
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Probe each key against the valid build rows.

    Returns (build_row, match): build_row[p] is the build-side row index
    whose key equals probe_keys[p] (undefined where match[p] is False);
    match[p] is the inner-join hit mask."""
    sort_key = jnp.where(build_valid, build_keys, KEY_SENTINEL)
    order = jnp.argsort(sort_key)
    sorted_keys = sort_key[order]
    pos = jnp.searchsorted(sorted_keys, probe_keys)
    cand = jnp.clip(pos, 0, sorted_keys.shape[0] - 1)
    match = (sorted_keys[cand] == probe_keys) & (probe_keys != KEY_SENTINEL)
    return order[cand], match


def range_join(
    build_keys: jnp.ndarray,  # int64 [B]
    build_valid: jnp.ndarray,  # bool [B]
    probe_keys: jnp.ndarray,  # int64 [P]
    max_dup: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Bounded many-to-many probe.

    Returns (build_rows [P, max_dup], match [P, max_dup]): slot j holds the
    j-th build row whose key equals the probe key (sorted run), match marks
    real slots.  max_dup must be >= the true max multiplicity among valid
    build rows (the planner computes it from the unfiltered column, a safe
    upper bound)."""
    sort_key = jnp.where(build_valid, build_keys, KEY_SENTINEL)
    order = jnp.argsort(sort_key)
    sorted_keys = sort_key[order]
    lo = jnp.searchsorted(sorted_keys, probe_keys)  # first slot of the run
    b = sorted_keys.shape[0]
    offs = jnp.arange(max_dup, dtype=lo.dtype)
    pos = lo[:, None] + offs[None, :]
    cand = jnp.clip(pos, 0, b - 1)
    # pos >= b guards the end clip: without it, a run ending exactly at the
    # array tail re-matches its last row through the clamped index
    # (review-caught double count)
    match = (
        (sorted_keys[cand] == probe_keys[:, None])
        & (probe_keys[:, None] != KEY_SENTINEL)
        & (pos < b)
    )
    return order[cand], match
