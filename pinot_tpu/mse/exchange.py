"""In-graph exchanges: the MSE data plane as XLA collectives.

Reference parity: pinot-query-runtime's BlockExchange strategies
(pinot-query-runtime/.../runtime/operator/exchange/{Hash,Broadcast,
Singleton,Random}Exchange.java) shipping serialized DataBlocks through gRPC
mailboxes (GrpcSendingMailbox.java:123) with back-pressure.

Re-design (SURVEY.md 2.6, 5.8): stage-to-stage rows never leave the device.
An exchange is a collective inside the one compiled program:

  broadcast  -> lax.all_gather over the data axes (BroadcastExchange): every
                device sees the whole (filtered) build side.
  hash       -> bucketize-by-key-hash + lax.all_to_all (HashExchange): rows
                land on the device that owns their key partition.

`axis` is one mesh axis name OR the 2-D (replica, shard) axes tuple
(parallel/mesh.data_axes): on the 2-D capacity mesh the exchange spans both
axes (rows shard jointly over them); on a replica row's 1-D submesh it is
automatically shard-local — the plan passes the row's own axis and no
exchange byte crosses the replica/DCN boundary.

Static shapes: a hash exchange cannot know its per-destination row counts at
trace time, so rows ride in fixed [ndev, capacity] buckets with a validity
mask; rows beyond capacity are DROPPED and counted.  On a non-zero overflow
the engine RE-RUNS the exchange with a doubled shuffleSlack (bounded —
mse/engine.py _run) — the TPU analog of mailbox back-pressure, which blocks
instead.
"""
from __future__ import annotations

from typing import Dict, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import lax

AxisSpec = Union[str, Sequence[str]]


def broadcast_rows(arrays: Dict[str, jnp.ndarray], axis: AxisSpec) -> Dict[str, jnp.ndarray]:
    """All devices receive every device's rows, concatenated in mesh order."""
    return {k: lax.all_gather(v, axis, tiled=True) for k, v in arrays.items()}


def hash_dest(key: jnp.ndarray, ndev: int) -> jnp.ndarray:
    """Destination device per row: murmur-style finalizer over the int64 key
    so strided key spaces (dates, ids) spread evenly."""
    k = key.astype(jnp.uint64)
    k = k ^ (k >> jnp.uint64(33))
    k = k * jnp.uint64(0xFF51AFD7ED558CCD)
    k = k ^ (k >> jnp.uint64(33))
    return (k % jnp.uint64(ndev)).astype(jnp.int32)


def hash_repartition(
    arrays: Dict[str, jnp.ndarray],
    dest: jnp.ndarray,
    ok: jnp.ndarray,
    ndev: int,
    capacity: int,
    axis: AxisSpec,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """HashExchange: send each valid row to device `dest[row]`.

    arrays: per-row payload arrays [N, ...] (same leading dim).
    dest:   int32 [N] in [0, ndev).
    ok:     bool [N] — invalid rows are not shipped.

    Returns (received_arrays, received_valid, overflow):
      received_arrays[k] is [ndev * capacity, ...] — this device's partition
      of the global row set; received_valid marks real rows; overflow is the
      GLOBAL number of rows dropped for exceeding per-destination capacity
      (psum'd — the engine re-runs with a doubled slack when > 0).
    """
    n = dest.shape[0]
    d = jnp.where(ok, dest, jnp.int32(ndev))  # invalid -> out-of-range, dropped
    order = jnp.argsort(d, stable=True)
    dsort = d[order]
    # rank within destination bucket = position - first index of that dest
    first = jnp.searchsorted(dsort, dsort, side="left")
    pos = jnp.arange(n, dtype=jnp.int32) - first.astype(jnp.int32)
    overflow_local = jnp.sum((dsort < ndev) & (pos >= capacity))
    overflow = lax.psum(overflow_local, axis)

    received: Dict[str, jnp.ndarray] = {}
    for name, a in arrays.items():
        buf = jnp.zeros((ndev, capacity) + a.shape[1:], dtype=a.dtype)
        buf = buf.at[dsort, pos].set(a[order], mode="drop")
        out = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0, tiled=True)
        received[name] = out.reshape((ndev * capacity,) + a.shape[1:])
    vbuf = jnp.zeros((ndev, capacity), dtype=bool)
    vbuf = vbuf.at[dsort, pos].set(True, mode="drop")
    valid = lax.all_to_all(vbuf, axis, split_axis=0, concat_axis=0, tiled=True)
    return received, valid.reshape(ndev * capacity), overflow
