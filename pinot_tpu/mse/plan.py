"""MSE logical planning: resolve joined tables, split filters, rewrite refs.

Reference parity: the front half of pinot-query-planner — QueryEnvironment's
Calcite pipeline (pinot-query-planner/.../query/QueryEnvironment.java:246)
resolving table/column references and pushing filters below the join
(PinotRuleSet filter-pushdown rules), before fragments are handed to workers.

Re-design: no Calcite.  The star-join shape (one fact table, N dimension
tables joined on fact FK = dim PK) is resolved directly: qualified names are
stripped to plain column names, every reference is assigned an owning table,
and WHERE conjuncts are pushed to the single table they touch.  The output
feeds one fused shard_map kernel (mse/engine.py) instead of shipping plan
fragments over gRPC.
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from pinot_tpu.query.ir import (
    AggregationSpec,
    Expr,
    ExprKind,
    FilterNode,
    FilterOp,
    JoinClause,
    OrderByExpr,
    QueryContext,
    map_expr_columns as _map_expr,
    map_filter_columns as _map_filter,
)


class JoinPlanError(ValueError):
    pass


@dataclass
class ResolvedJoin:
    table: str  # physical dimension (build-side) table name
    join_type: str  # "inner" | "left"
    fact_key: str  # plain probe-side column name (fact OR parent dim)
    dim_key: str  # plain dim column name (build side)
    # which table owns the probe key: the fact table (star) or an
    # earlier-joined dimension (snowflake chain — LookupJoinOperator's
    # dim->dim analog); joins are topologically ordered so the parent's
    # gathered rows exist before this join probes through them
    probe_owner: str = ""


@dataclass
class ResolvedQuery:
    ctx: QueryContext  # rewritten: plain column names everywhere
    fact: str
    joins: List[ResolvedJoin]
    owner: Dict[str, str]  # plain column name -> owning table
    fact_filter: Optional[FilterNode]
    dim_filters: Dict[str, Optional[FilterNode]] = field(default_factory=dict)


def resolve(ctx: QueryContext, schemas: Dict[str, "object"]) -> ResolvedQuery:
    """schemas: table name -> object with .column_names (Schema/StackedTable)."""
    # schema-free static validation (function existence/arity, agg nesting,
    # limit sanity) before join resolution; column ownership is checked by
    # resolve_name below against the per-table column sets
    from pinot_tpu.analysis.plan_check import check_plan

    check_plan(ctx)
    fact = ctx.table
    if fact not in schemas:
        raise JoinPlanError(f"table {fact!r} is not registered")

    # -- self-joins: duplicate physical tables get per-ALIAS facades -------
    # (columns renamed '{alias}${col}', storage shared — StackedTable
    # .aliased_view; the reference disambiguates in Calcite scope binding)
    phys = [fact] + [j.table for j in ctx.joins]
    dup_phys = {t for t in phys if phys.count(t) > 1}
    joins_in: List[JoinClause] = list(ctx.joins)
    alias_prefix: Dict[str, str] = {}  # facade table name -> column prefix
    if dup_phys:
        rewritten: List[JoinClause] = []
        for j in ctx.joins:
            if j.table in dup_phys:
                if not j.alias:
                    raise JoinPlanError(
                        f"self-join on {j.table!r} requires an alias for each occurrence"
                    )
                fname = f"{j.table}@{j.alias}"
                if fname not in schemas:
                    base = schemas[j.table]
                    if not hasattr(base, "aliased_view"):
                        raise JoinPlanError(
                            f"self-join on {j.table!r} requires StackedTable registration"
                        )
                    schemas[fname] = base.aliased_view(j.alias)
                alias_prefix[fname] = j.alias
                rewritten.append(dataclasses.replace(j, table=fname))
            else:
                rewritten.append(j)
        joins_in = rewritten

    alias_map: Dict[str, str] = {ctx.table_alias or fact: fact, fact: fact}
    tables: List[str] = [fact]
    for j in joins_in:
        if j.table not in schemas:
            raise JoinPlanError(f"joined table {j.table!r} is not registered")
        if j.table in tables:
            raise JoinPlanError(
                f"table {j.table!r} joined twice; alias each occurrence of a self-join"
            )
        tables.append(j.table)
        alias_map[j.alias or j.table] = j.table
        alias_map.setdefault(j.table, j.table)

    col_sets = {t: set(schemas[t].column_names) for t in tables}

    def resolve_name(name: str) -> "tuple[str, str]":
        if name == "*":
            return name, fact
        if "." in name:
            q, c = name.split(".", 1)
            t = alias_map.get(q)
            if t is None:
                raise JoinPlanError(f"unknown table alias {q!r} in {name!r}")
            if c not in col_sets[t]:
                pc = f"{alias_prefix[t]}${c}" if t in alias_prefix else None
                if pc is not None and pc in col_sets[t]:
                    return pc, t
                raise JoinPlanError(f"table {t!r} has no column {c!r}")
            return c, t
        owners = [t for t in tables if name in col_sets[t]]
        if not owners:
            raise JoinPlanError(f"unknown column {name!r}")
        if len(owners) > 1:
            raise JoinPlanError(
                f"column {name!r} exists in {owners}; qualify it (alias.column)"
            )
        return name, owners[0]

    owner: Dict[str, str] = {}

    def note(plain: str, t: str) -> None:
        prev = owner.setdefault(plain, t)
        if prev != t:
            raise JoinPlanError(
                f"column name {plain!r} resolves to both {prev!r} and {t!r}; "
                "identically-named columns across joined tables are unsupported"
            )

    def rewrite_col(e: Expr) -> Expr:
        plain, t = resolve_name(e.op)
        note(plain, t) if plain != "*" else None
        return e if e.op == plain else Expr.col(plain)

    def rw_expr(e: Expr) -> Expr:
        return _map_expr(e, rewrite_col)

    def rw_agg(s: AggregationSpec) -> AggregationSpec:
        return dataclasses.replace(
            s,
            expr=rw_expr(s.expr) if s.expr is not None else None,
            filter=_map_filter(s.filter, rewrite_col),
        )

    select_list = [rw_agg(s) if isinstance(s, AggregationSpec) else rw_expr(s) for s in ctx.select_list]
    group_by = [rw_expr(g) for g in ctx.group_by]
    where = _map_filter(ctx.filter, rewrite_col)
    having = _map_filter(ctx.having, rewrite_col)
    order_by = [OrderByExpr(rw_expr(o.expr), o.ascending, o.nulls_last) for o in ctx.order_by]
    extra_aggs = [rw_agg(s) for s in ctx.extra_aggregations]

    joins: List[ResolvedJoin] = []
    for j in joins_in:
        lk, lt = resolve_name(j.left_key.op)
        rk, rt = resolve_name(j.right_key.op)
        note(lk, lt)
        note(rk, rt)
        # normalize orientation: fact (or any non-this-dim) side is the probe
        if rt == j.table and lt != j.table:
            fact_key, fk_owner, dim_key = lk, lt, rk
        elif lt == j.table and rt != j.table:
            fact_key, fk_owner, dim_key = rk, rt, lk
        else:
            raise JoinPlanError(
                f"JOIN ON for {j.table!r} must link it to another table "
                f"(got {j.left_key} = {j.right_key})"
            )
        joins.append(ResolvedJoin(j.table, j.join_type, fact_key, dim_key, probe_owner=fk_owner))

    # -- topological order: snowflake parents before their children --------
    # (dim->dim chains probe through the PARENT's gathered rows; a chain's
    # probe owner must itself be joined before the child runs)
    ordered: List[ResolvedJoin] = []
    pending = list(joins)
    placed = {fact}
    while pending:
        progressed = False
        for j in list(pending):
            if j.probe_owner in placed:
                ordered.append(j)
                placed.add(j.table)
                pending.remove(j)
                progressed = True
        if not progressed:
            cyc = [(j.table, j.probe_owner) for j in pending]
            raise JoinPlanError(
                f"join graph is not a tree rooted at {fact!r}: {cyc} "
                "(each join's probe key must reference the fact table or an "
                "earlier-joined dimension)"
            )
    joins = ordered

    # -- filter pushdown: split top-level AND conjuncts by owning table ----
    fact_filter: Optional[FilterNode] = None
    dim_filters: Dict[str, Optional[FilterNode]] = {j.table: None for j in joins}

    def conjuncts(node: Optional[FilterNode]) -> List[FilterNode]:
        if node is None:
            return []
        if node.op is FilterOp.AND:
            out: List[FilterNode] = []
            for c in node.children:
                out.extend(conjuncts(c))
            return out
        return [node]

    per_table: Dict[str, List[FilterNode]] = {t: [] for t in tables}
    for c in conjuncts(where):
        touched = {owner[col] for col in c.columns() if col != "*"}
        if len(touched) > 1:
            raise JoinPlanError(
                f"WHERE predicate {c.predicates()} spans tables {sorted(touched)}; "
                "cross-table predicates (non-equi join conditions) are unsupported"
            )
        t = next(iter(touched)) if touched else fact
        per_table[t].append(c)

    def combine(nodes: List[FilterNode]) -> Optional[FilterNode]:
        if not nodes:
            return None
        if len(nodes) == 1:
            return nodes[0]
        return FilterNode.and_(*nodes)

    fact_filter = combine(per_table[fact])
    for j in joins:
        dim_filters[j.table] = combine(per_table[j.table])
        if j.join_type == "left" and dim_filters[j.table] is not None:
            # a WHERE filter on the dim side of a LEFT JOIN would silently
            # change semantics (NULL rows fail predicates) — the reference
            # keeps such filters above the join; we reject for now
            raise JoinPlanError(
                f"WHERE filter on LEFT JOIN dimension {j.table!r} is unsupported "
                "(it would not preserve unmatched rows)"
            )

    ctx2 = dataclasses.replace(
        ctx,
        select_list=select_list,
        group_by=group_by,
        filter=where,
        having=having,
        order_by=order_by,
        extra_aggregations=extra_aggs,
        joins=list(ctx.joins),
    )
    return ResolvedQuery(
        ctx=ctx2,
        fact=fact,
        joins=joins,
        owner=owner,
        fact_filter=fact_filter,
        dim_filters=dim_filters,
    )
