"""Multi-stage engine (MSE): joins + exchanges as in-graph collectives.

Reference parity: pinot-query-planner + pinot-query-runtime (SURVEY.md 2.3).
"""
from pinot_tpu.mse.engine import MultiStageEngine
from pinot_tpu.mse.plan import JoinPlanError

__all__ = ["MultiStageEngine", "JoinPlanError"]
